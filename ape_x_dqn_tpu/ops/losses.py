"""Double-Q targets, TD errors, losses and priorities — pure functions.

Implements the intended semantics of reference learner.py:29-52:
  * n-step double-Q target  G_t = R_{t→t+n} + D_n · Q_target(S', argmax_a Q(S',a))
    (reference learner.py:43-45), with the terminal mask folded into D_n
    (the reference has no done-mask — SURVEY §2.8).
  * TD error δ = Q(S_t, A_t) − G_t and loss = mean(w · ℓ(δ)) where ℓ is
    ½δ² for parity with the reference (learner.py:47-48) or Huber (the
    north-star option), and w are importance-sampling weights (the
    reference's README-TODO, config key parameters.json:30 read by nothing).
  * Per-transition priorities |δ| (the reference collapses them to one value
    via a dict-comprehension bug — learner.py:50, SURVEY §2.8).

Everything here is shape-polymorphic, jit-friendly, and differentiable only
through the online-net Q values (targets are lax.stop_gradient'ed).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def double_q_target(
    q_online_next: jax.Array,
    q_target_next: jax.Array,
    rewards: jax.Array,
    discounts: jax.Array,
) -> jax.Array:
    """n-step double-Q bootstrap target.

    Args:
      q_online_next: float [B, A] — online net at S_{t+n} (action selection).
      q_target_next: float [B, A] — target net at S_{t+n} (action evaluation).
      rewards: float [B] — accumulated n-step returns R_{t→t+n}.
      discounts: float [B] — bootstrap discount γ^n·(terminal mask).

    Returns:
      float [B] targets, stop-gradient'ed.
    """
    best_actions = jnp.argmax(q_online_next, axis=-1)
    bootstrap = jnp.take_along_axis(
        q_target_next, best_actions[:, None], axis=-1
    )[:, 0]
    return jax.lax.stop_gradient(rewards + discounts * bootstrap)


def max_q_target(
    q_next: jax.Array, rewards: jax.Array, discounts: jax.Array
) -> jax.Array:
    """Plain max-Q bootstrap — the actor-side initial-priority rule
    (reference actor.py:138-142 uses max-Q, not double-Q)."""
    return jax.lax.stop_gradient(rewards + discounts * jnp.max(q_next, axis=-1))


def td_error(q_values: jax.Array, actions: jax.Array, targets: jax.Array) -> jax.Array:
    """δ = Q(S_t, A_t) − G_t, float [B]."""
    chosen = jnp.take_along_axis(q_values, actions[:, None].astype(jnp.int32), axis=-1)[:, 0]
    return chosen - targets


def huber(delta: jax.Array, kappa: float = 1.0) -> jax.Array:
    """Per-element Huber loss ℓ_κ(δ)."""
    abs_d = jnp.abs(delta)
    quad = jnp.minimum(abs_d, kappa)
    return 0.5 * quad**2 + kappa * (abs_d - quad)


def squared(delta: jax.Array) -> jax.Array:
    """Parity loss: ½δ² (reference learner.py:48 — squared, not Huber)."""
    return 0.5 * delta**2


def td_loss(
    delta: jax.Array,
    is_weights: jax.Array | None = None,
    kind: str = "huber",
    huber_kappa: float = 1.0,
) -> jax.Array:
    """Weighted mean TD loss. ``kind`` in {"huber", "squared"} (static)."""
    if kind == "huber":
        per = huber(delta, huber_kappa)
    elif kind == "squared":
        per = squared(delta)
    else:
        raise ValueError(f"unknown loss kind: {kind}")
    if is_weights is not None:
        per = per * is_weights
    return jnp.mean(per)


def priorities_from_td(delta: jax.Array, epsilon: float = 1e-6) -> jax.Array:
    """Replay priorities p = |δ| + ε, per transition (not collapsed)."""
    return jnp.abs(delta) + epsilon
