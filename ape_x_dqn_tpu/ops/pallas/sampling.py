"""Pallas TPU kernel: stratified inverse-CDF sampling over priorities.

The device replay's hot op is "given priorities p[0..C) and B stratified
target masses, find the B leaf indices whose prefix-sum intervals contain
them".  The XLA spelling (``cumsum`` + ``searchsorted``) materializes the
full C-length prefix array in HBM — a write + re-read of 4·C bytes the
kernel below avoids: it streams the priority array through VMEM **once**
(sequential grid over (R,128) tiles, running carry in SMEM — TPU grid
programs execute in order, which is what makes the carry legal), builds the
tile's inclusive prefix with an unrolled Hillis-Steele shift-add (``cumsum``
has no Mosaic lowering), and resolves each target with a monotone count
``pos = Σ[prefix ≤ rel]`` — no argmax, no reshape, nothing the TPU
lowering lacks.  HBM traffic drops from ~3 passes to 1.

Written per /opt/skills/guides/pallas_guide.md idioms (sequential-grid
carry, SMEM scratch, ``@pl.when`` predication).  ``sample_indices`` picks
the kernel on TPU and falls back to the XLA spelling elsewhere (interpret
mode keeps the kernel testable on CPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

LANES = 128
ROWS = 8
BLOCK = ROWS * LANES  # priorities per grid step; 4 KB f32 in VMEM


def _xla_sample(priorities: jax.Array, targets: jax.Array) -> jax.Array:
    """Reference spelling: full cumsum + searchsorted (side='right' so a
    target exactly on a boundary selects the next nonzero-mass leaf)."""
    cdf = jnp.cumsum(priorities)
    idx = jnp.searchsorted(cdf, targets, side="right")
    return jnp.clip(idx, 0, priorities.shape[0] - 1).astype(jnp.int32)


def _tile_inclusive_prefix(x: jax.Array) -> jax.Array:
    """Inclusive prefix over a (ROWS, LANES) tile in row-major order.

    The TPU-native prefix sum: a triangular matmul on the MXU.
    ``cumsum`` has no Mosaic lowering and shifted-concat Hillis-Steele trips
    offset constraints, but prefix[r, j] = Σ_{k≤j} x[r, k] is exactly
    ``x @ U`` with U upper-triangular ones — one 128×128 systolic pass.
    Row offsets are the same trick on the (tiny) row-total vector with a
    strictly-lower-triangular matrix.
    """
    upper = jnp.triu(jnp.ones((LANES, LANES), jnp.float32))       # k<=j
    prefix = jax.lax.dot(x, upper, precision=jax.lax.Precision.HIGHEST)
    row_tot = x @ jnp.ones((LANES, 1), jnp.float32)               # (ROWS, 1)
    strictly_lower = jnp.tril(jnp.ones((ROWS, ROWS), jnp.float32), k=-1)
    row_excl = jax.lax.dot(
        strictly_lower, row_tot, precision=jax.lax.Precision.HIGHEST
    )                                                             # (ROWS, 1)
    return prefix + row_excl


def _kernel(p_ref, t_ref, out_ref, carry_ref):
    """One grid step: resolve all targets landing in this priority tile.

    carry_ref (SMEM, (1,)) holds the total mass of all previous tiles —
    valid because TPU grid steps run sequentially.
    """
    from jax.experimental import pallas as pl

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        carry_ref[0] = 0.0
        # Initialize before the first read of out_ref below: a target past
        # the total mass (callers clamp, but belt-and-braces) resolves to
        # the last leaf instead of uninitialized memory.
        out_ref[:] = jnp.full_like(out_ref, pl.num_programs(0) * BLOCK - 1)

    base = carry_ref[0]
    prefix = _tile_inclusive_prefix(p_ref[:])   # (ROWS, LANES)
    tile_sum = prefix[ROWS - 1, LANES - 1]
    targets = t_ref[:]                          # (1, B) — B on the lane dim
    rel = targets - base
    in_tile = (targets >= base) & (targets < base + tile_sum)
    # Monotone count: index of first prefix entry > rel (== #entries <= rel).
    # Layout: (ROWS, LANES, B) with B in lanes; reduce the tile axes.
    le = (prefix[:, :, None] <= rel[0][None, None, :]).astype(jnp.int32)
    pos = jnp.sum(le, axis=(0, 1))[None, :]     # (1, B)
    pos = jnp.minimum(pos, BLOCK - 1)
    global_idx = (step * BLOCK + pos).astype(jnp.int32)
    out_ref[:] = jnp.where(in_tile, global_idx, out_ref[:])
    carry_ref[0] = base + tile_sum


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_sample(priorities: jax.Array, targets: jax.Array,
                   interpret: bool = False) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C = priorities.shape[0]
    if C % BLOCK != 0:
        pad = BLOCK - C % BLOCK
        priorities = jnp.concatenate([priorities, jnp.zeros((pad,), priorities.dtype)])
    C_padded = priorities.shape[0]
    B = targets.shape[0]
    grid = C_padded // BLOCK
    p2d = priorities.astype(jnp.float32).reshape(grid * ROWS, LANES)
    t2d = targets.astype(jnp.float32)[None, :]
    out = pl.pallas_call(
        _kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((ROWS, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, B), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, B), jnp.int32),
        scratch_shapes=[pltpu.SMEM((1,), jnp.float32)],
        interpret=interpret,
    )(p2d, t2d)
    return jnp.clip(out[0], 0, C - 1)


def sample_indices(
    priorities: jax.Array,
    targets: jax.Array,
    use_pallas: bool | None = None,
) -> jax.Array:
    """Stratified inverse-CDF lookup: indices [B] for target masses [B].

    ``use_pallas=None`` → kernel on TPU, XLA spelling elsewhere.
    """
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        return _pallas_sample(priorities, targets)
    return _xla_sample(priorities, targets)
