"""Pallas TPU kernel: stratified inverse-CDF sampling over priorities.

The device replay's hot op is "given priorities p[0..C) and B stratified
target masses, find the B leaf indices whose prefix-sum intervals contain
them".  The XLA spelling (``cumsum`` + ``searchsorted``) materializes the
full C-length prefix array in HBM — a write + re-read of 4·C bytes the
kernel below avoids: it streams the priority array through VMEM **once**
(sequential grid over (R,128) tiles, running carry in SMEM — TPU grid
programs execute in order, which is what makes the carry legal), builds the
tile's inclusive prefix with an unrolled Hillis-Steele shift-add (``cumsum``
has no Mosaic lowering), and resolves each target with a monotone count
``pos = Σ[prefix ≤ rel]`` — no argmax, no reshape, nothing the TPU
lowering lacks.  HBM traffic drops from ~3 passes to 1.

Written per /opt/skills/guides/pallas_guide.md idioms (sequential-grid
carry, SMEM scratch, ``@pl.when`` predication).

Hardware measurement (round 2, real v5e at C=2M) found the streaming
kernel's sequential grid pays ~1 µs/tile of grid overhead and the flat
spellings pay O(C) HBM traffic per call, so the production default in
``sample_indices`` is now ``_two_level_sample`` — a radix-√C two-level
inverse-CDF (the TPU-native sum-tree) that does O(C/chunk)+O(B·chunk)
work.  The Pallas kernel and flat XLA spelling remain as explicitly
selectable paths (`use_pallas=True/False`): the kernel documents the
single-pass bandwidth experiment and runs under interpret mode on CPU;
the flat spelling is the test oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

LANES = 128
ROWS = 8
BLOCK = ROWS * LANES  # priorities per grid step; 4 KB f32 in VMEM


def _xla_sample(priorities: jax.Array, targets: jax.Array) -> jax.Array:
    """Reference spelling: full cumsum + searchsorted (side='right' so a
    target exactly on a boundary selects the next nonzero-mass leaf)."""
    cdf = jnp.cumsum(priorities)
    idx = jnp.searchsorted(cdf, targets, side="right")
    return jnp.clip(idx, 0, priorities.shape[0] - 1).astype(jnp.int32)


def _tile_inclusive_prefix(x: jax.Array) -> jax.Array:
    """Inclusive prefix over a (ROWS, LANES) tile in row-major order.

    The TPU-native prefix sum: a triangular matmul on the MXU.
    ``cumsum`` has no Mosaic lowering and shifted-concat Hillis-Steele trips
    offset constraints, but prefix[r, j] = Σ_{k≤j} x[r, k] is exactly
    ``x @ U`` with U upper-triangular ones — one 128×128 systolic pass.
    Row offsets are the same trick on the (tiny) row-total vector with a
    strictly-lower-triangular matrix.
    """
    upper = jnp.triu(jnp.ones((LANES, LANES), jnp.float32))       # k<=j
    prefix = jax.lax.dot(x, upper, precision=jax.lax.Precision.HIGHEST)
    row_tot = x @ jnp.ones((LANES, 1), jnp.float32)               # (ROWS, 1)
    strictly_lower = jnp.tril(jnp.ones((ROWS, ROWS), jnp.float32), k=-1)
    row_excl = jax.lax.dot(
        strictly_lower, row_tot, precision=jax.lax.Precision.HIGHEST
    )                                                             # (ROWS, 1)
    return prefix + row_excl


def _kernel(p_ref, t_ref, out_ref, carry_ref):
    """One grid step: resolve all targets landing in this priority tile.

    carry_ref (SMEM, (1,)) holds the total mass of all previous tiles —
    valid because TPU grid steps run sequentially.
    """
    from jax.experimental import pallas as pl

    step = pl.program_id(0)

    @pl.when(step == 0)
    def _():
        carry_ref[0] = 0.0
        # Initialize before the first read of out_ref below: a target past
        # the total mass (callers clamp, but belt-and-braces) resolves to
        # the last leaf instead of uninitialized memory.
        out_ref[:] = jnp.full_like(out_ref, pl.num_programs(0) * BLOCK - 1)

    base = carry_ref[0]
    prefix = _tile_inclusive_prefix(p_ref[:])   # (ROWS, LANES)
    tile_sum = prefix[ROWS - 1, LANES - 1]
    targets = t_ref[:]                          # (1, B) — B on the lane dim
    rel = targets - base
    in_tile = (targets >= base) & (targets < base + tile_sum)
    # Monotone count: index of first prefix entry > rel (== #entries <= rel).
    # Layout: (ROWS, LANES, B) with B in lanes; reduce the tile axes.
    le = (prefix[:, :, None] <= rel[0][None, None, :]).astype(jnp.int32)
    pos = jnp.sum(le, axis=(0, 1))[None, :]     # (1, B)
    pos = jnp.minimum(pos, BLOCK - 1)
    global_idx = (step * BLOCK + pos).astype(jnp.int32)
    out_ref[:] = jnp.where(in_tile, global_idx, out_ref[:])
    carry_ref[0] = base + tile_sum


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pallas_sample(priorities: jax.Array, targets: jax.Array,
                   interpret: bool = False) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    C = priorities.shape[0]
    if C % BLOCK != 0:
        pad = BLOCK - C % BLOCK
        priorities = jnp.concatenate([priorities, jnp.zeros((pad,), priorities.dtype)])
    C_padded = priorities.shape[0]
    B = targets.shape[0]
    grid = C_padded // BLOCK
    p2d = priorities.astype(jnp.float32).reshape(grid * ROWS, LANES)
    t2d = targets.astype(jnp.float32)[None, :]
    out = pl.pallas_call(
        _kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((ROWS, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, B), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, B), lambda i: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, B), jnp.int32),
        scratch_shapes=[pltpu.SMEM((1,), jnp.float32)],
        interpret=interpret,
    )(p2d, t2d)
    return jnp.clip(out[0], 0, C - 1)


def _two_level_sample(priorities: jax.Array, targets: jax.Array,
                      chunk: int = 1024) -> jax.Array:
    """Two-level inverse-CDF: the TPU-native sum-tree.

    A pointer-chasing O(log C) tree serializes on the VPU, and a flat cumsum
    is O(C) of HBM traffic per call — measured 1.8–3.2 ms at C=2M on a real
    v5e, which caps the fused learner at ~500 steps/s.  The two-level split
    does O(C/chunk) + O(B·chunk) work instead: one bandwidth-friendly
    row-reduce builds per-chunk masses, a tiny cumsum picks each target's
    chunk, and a B×chunk row cumsum resolves the leaf — ~5 µs at C=100k.
    This is exactly a radix-√C sum-tree with both levels vectorized.

    Same proportional-mass semantics as ``_xla_sample`` (indices may differ
    by a few leaves where float32 accumulation order shifts a boundary —
    immaterial for mass-proportional sampling).
    """
    C = priorities.shape[0]
    if C % chunk != 0:
        pad = chunk - C % chunk
        priorities = jnp.concatenate(
            [priorities, jnp.zeros((pad,), priorities.dtype)]
        )
    rows = priorities.reshape(-1, chunk).astype(jnp.float32)  # [R, chunk]
    row_mass = jnp.sum(rows, axis=1)                          # [R]
    row_cdf = jnp.cumsum(row_mass)
    targets = targets.astype(jnp.float32)
    r = jnp.clip(
        jnp.searchsorted(row_cdf, targets, side="right"), 0, rows.shape[0] - 1
    )
    rel = targets - (row_cdf[r] - row_mass[r])                # mass within row
    picked = rows[r]                                          # [B, chunk] gather
    cdf = jnp.cumsum(picked, axis=1)
    # side="right" per row: count of prefix entries <= rel.
    pos = jnp.sum((cdf <= rel[:, None]).astype(jnp.int32), axis=1)
    pos = jnp.minimum(pos, chunk - 1)
    return jnp.clip(r * chunk + pos, 0, C - 1).astype(jnp.int32)


def sample_indices(
    priorities: jax.Array,
    targets: jax.Array,
    use_pallas: bool | None = None,
) -> jax.Array:
    """Stratified inverse-CDF lookup: indices [B] for target masses [B].

    Default is the two-level sampler everywhere: on a real v5e it beats both
    the flat-cumsum XLA spelling and the streaming Pallas kernel by ~2
    orders of magnitude at large C (all three were measured on hardware;
    the Pallas kernel's sequential grid pays ~1 µs/tile of grid overhead).
    ``use_pallas=True`` forces the Pallas kernel (kept for the bandwidth
    experiment it documents); ``use_pallas=False`` forces the flat XLA
    spelling (the oracle for tests).
    """
    if use_pallas is None:
        return _two_level_sample(priorities, targets)
    if use_pallas:
        return _pallas_sample(priorities, targets)
    return _xla_sample(priorities, targets)
