"""Vectorized n-step return construction.

The reference accumulates n-step returns with an O(n²)-per-step Python loop
inside ``ExperienceBuffer.update_buffer`` (reference: actor.py:29-43) and emits
**non-overlapping** windows (the window advances n steps per emitted
transition).  It also stores a wrong bootstrap discount (γ^(n−1) instead of
γ^n) and bootstraps through terminals (SURVEY §2.8).

Here the same math is a single ``lax.scan``-free vectorized computation over a
rollout segment — O(T·n) fused element-wise work that XLA vectorizes, usable
both on device (inside a jitted actor rollout) and on host via numpy semantics.

Definitions, for per-step reward r_t and per-step discount d_t = γ·(1−done_t):

    R^{(n)}_t = Σ_{k=0}^{n-1} (Π_{j<k} d_{t+j}) · r_{t+k}
    D^{(n)}_t = Π_{j=0}^{n-1} d_{t+j}              (0 if any step terminated)
    S'_t      = obs_{t+n}

so the learner target is exactly ``R + D · Q_target(S')`` with no done-mask
special case (the mask is folded into D).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ape_x_dqn_tpu.types import NStepTransition


def nstep_returns(rewards: jax.Array, discounts: jax.Array, n: int):
    """Compute n-step returns and bootstrap discounts for every start index.

    Args:
      rewards: float32 [T] — reward received after step t.
      discounts: float32 [T] — γ·(1−done_t) for step t.
      n: the n-step horizon (static).

    Returns:
      (returns, boot_discounts): each float32 [T - n + 1]; entry t covers the
      window [t, t+n).
    """
    T = rewards.shape[0]
    if T < n:
        raise ValueError(f"rollout length {T} < n-step horizon {n}")
    out_len = T - n + 1
    # returns_k / disc_k built iteratively over the (static, small) horizon:
    #   acc_{k+1} = acc_k + cumdisc_k * r_{t+k};  cumdisc_{k+1} = cumdisc_k * d_{t+k}
    acc = jnp.zeros((out_len,), jnp.float32)
    cumdisc = jnp.ones((out_len,), jnp.float32)
    for k in range(n):
        r_k = jax.lax.dynamic_slice_in_dim(rewards, k, out_len)
        d_k = jax.lax.dynamic_slice_in_dim(discounts, k, out_len)
        acc = acc + cumdisc * r_k
        cumdisc = cumdisc * d_k
    return acc, cumdisc


def build_nstep_transitions(
    obs: jax.Array,
    actions: jax.Array,
    rewards: jax.Array,
    discounts: jax.Array,
    bootstrap_obs: jax.Array,
    n: int,
    stride: int = 1,
) -> NStepTransition:
    """Build n-step transitions from a rollout segment.

    Args:
      obs: uint8 [T, *obs_shape] — observations S_0..S_{T-1}.
      actions: int32 [T].
      rewards: float32 [T].
      discounts: float32 [T] — γ·(1−done_t).
      bootstrap_obs: uint8 [*obs_shape] — the single observation S_T
        immediately after the segment.  Start indices run 0..T−n, so the
        bootstrap frames needed are S_n..S_T; all but S_T are sliced from
        ``obs`` itself.  At episode boundaries the bootstrap obs content is
        irrelevant because the bootstrap discount is 0.
      n: horizon.
      stride: 1 for overlapping windows (standard Ape-X), ``n`` for the
        reference's non-overlapping emission (reference actor.py:44-70).

    Returns:
      NStepTransition with batch dim ceil((T-n+1)/stride).
    """
    returns, boot = nstep_returns(rewards, discounts, n)
    all_obs = jnp.concatenate([obs, bootstrap_obs[None]], axis=0)
    out_len = returns.shape[0]
    starts = jnp.arange(0, out_len, stride)
    next_obs = all_obs[starts + n]
    return NStepTransition(
        obs=obs[starts],
        action=actions[starts],
        reward=returns[starts],
        discount=boot[starts],
        next_obs=next_obs,
    )


def nstep_returns_np(rewards: "np.ndarray", discounts: "np.ndarray", n: int):
    """Numpy twin of :func:`nstep_returns` for host-side actor paths.

    Actors live on the host thread next to the TPU learner; running their
    n-step math through jnp would compile and dispatch tiny device programs
    on the hot rollout path.  Same semantics, leading axis is time; extra
    trailing axes (e.g. an actor axis [T, N]) broadcast through.
    """
    import numpy as np

    T = rewards.shape[0]
    if T < n:
        raise ValueError(f"rollout length {T} < n-step horizon {n}")
    out_len = T - n + 1
    acc = np.zeros_like(rewards[:out_len], dtype=np.float32)
    cumdisc = np.ones_like(discounts[:out_len], dtype=np.float32)
    for k in range(n):
        acc += cumdisc * rewards[k : k + out_len]
        cumdisc = cumdisc * discounts[k : k + out_len]
    return acc, cumdisc


def nstep_returns_reference(rewards, discounts, n):
    """Slow pure-Python oracle for tests (mirrors the paper definition)."""
    T = len(rewards)
    outs, boots = [], []
    for t in range(T - n + 1):
        acc, cd = 0.0, 1.0
        for k in range(n):
            acc += cd * float(rewards[t + k])
            cd *= float(discounts[t + k])
        outs.append(acc)
        boots.append(cd)
    return outs, boots
