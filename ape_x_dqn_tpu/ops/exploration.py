"""Exploration: the Ape-X per-actor ε-ladder and ε-greedy action selection.

The ladder follows reference actor.py:111-114: actor i of N uses
    ε_i = ε^(1 + α·i/(N−1))          (ε=0.4, α=7 — parameters.json:12-13)
which is the Ape-X paper's schedule.  For N == 1 the exponent is 1 (the
reference would divide by zero; we define the single-actor case as ε itself).

Action selection is fully vectorized so a fleet of actors can pick actions in
one fused op on device (batch of q-value rows + batch of ε's).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def epsilon_ladder(base_epsilon: float, alpha: float, num_actors: int) -> jnp.ndarray:
    """float32 [num_actors] of per-actor ε values."""
    if num_actors <= 0:
        raise ValueError("num_actors must be positive")
    if num_actors == 1:
        return jnp.asarray([base_epsilon], jnp.float32)
    i = jnp.arange(num_actors, dtype=jnp.float32)
    exponent = 1.0 + alpha * i / (num_actors - 1)
    return jnp.power(base_epsilon, exponent).astype(jnp.float32)


def epsilon_greedy(
    rng: jax.Array, q_values: jax.Array, epsilon: jax.Array
) -> jax.Array:
    """Batched ε-greedy (reference actor.py:121-125, vectorized).

    Args:
      rng: PRNGKey.
      q_values: float [B, A].
      epsilon: float [] or [B].

    Returns:
      int32 [B] actions.
    """
    B, A = q_values.shape
    explore_rng, action_rng = jax.random.split(rng)
    greedy = jnp.argmax(q_values, axis=-1).astype(jnp.int32)
    random_actions = jax.random.randint(action_rng, (B,), 0, A, dtype=jnp.int32)
    explore = jax.random.uniform(explore_rng, (B,)) < epsilon
    return jnp.where(explore, random_actions, greedy)
