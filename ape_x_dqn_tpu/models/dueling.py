"""Dueling Q-networks as Flax modules.

Capability parity with reference duelling_network.py:3-28 (the 28-line torch
module), TPU-first:
  * Conv torso Conv(8×8/4) → Conv(4×4/2) → Conv(3×3/1) → flatten → two
    512-unit streams → value head (1) + advantage head (A).  Default channel
    widths 64/64/64 match the reference (NOT the Nature-DQN 32/64/64 —
    SURVEY §2 component 5); ``channels=(32, 64, 64)`` gives the Nature stack.
  * Aggregation is the *intended* per-row mean:  Q = V + (A − mean_a A)
    (the reference's ``advantage.sum()`` reduces over the whole batch —
    duelling_network.py:27, defect register SURVEY §2.8).
  * ``forward`` returns ``(value, advantage, q)`` matching the reference's
    triple return (duelling_network.py:28); callers that only need Q use
    ``.q_values()``.
  * Compute dtype is configurable (bfloat16 by default on TPU — MXU-native);
    params stay float32.  uint8 inputs are normalized inside the module so
    frames travel HBM as bytes.
  * NHWC layout (TPU conv-friendly), vs the reference's NCHW.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn


class DuelingOutput(NamedTuple):
    """(value, advantage, q) — index [2] for Q, as reference callers do.

    A NamedTuple so it is a registered JAX pytree: network outputs can cross
    jit/vmap/scan boundaries intact (e.g. returned from a jitted rollout).
    """

    value: jax.Array
    advantage: jax.Array
    q: jax.Array


def _dueling_aggregate(value: jax.Array, advantage: jax.Array) -> jax.Array:
    return value + advantage - jnp.mean(advantage, axis=-1, keepdims=True)


class DuelingDQN(nn.Module):
    """Convolutional dueling Q-network for image observations.

    Attributes:
      num_actions: size of the action space.
      channels: conv channel widths (reference parity default (64, 64, 64)).
      hidden: width of each dueling stream's hidden layer (reference: 512).
      compute_dtype: activation dtype — bfloat16 rides the MXU natively.
      param_dtype: parameter storage dtype.  bfloat16 halves the param HBM
        read per forward/backward (the fused step is bandwidth-bound); pair
        it with ``train_step.with_float32_master`` so updates accumulate in
        float32.
    """

    num_actions: int
    channels: Sequence[int] = (64, 64, 64)
    hidden: int = 512
    compute_dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
        # Accept NHWC uint8 or float; [B, H, W, C].  Guard against the
        # reference's NCHW layout, which otherwise fails deep inside flax.
        if x.ndim != 4:
            raise ValueError(f"expected NHWC [B, H, W, C] observations, got shape {x.shape}")
        if x.shape[1] <= 4 and x.shape[3] > 4 and x.shape[2] == x.shape[3]:
            # A tiny axis-1 extent with a large *square* trailing pair is the
            # NCHW frame signature (B, C, H, W); square spatial dims keep
            # legitimate small-H NHWC inputs like (B, 4, 4, 8) usable.
            raise ValueError(
                f"observations look NCHW (shape {x.shape}); this framework uses "
                "NHWC [B, H, W, C] — transpose with x.transpose(0, 2, 3, 1)"
            )
        if x.dtype == jnp.uint8:
            x = x.astype(self.compute_dtype) / 255.0
        else:
            x = x.astype(self.compute_dtype)
        kernels = ((8, 8), (4, 4), (3, 3))
        strides = ((4, 4), (2, 2), (1, 1))
        if len(self.channels) != len(kernels):
            raise ValueError(
                f"channels must have exactly {len(kernels)} entries, got {self.channels}"
            )
        for ch, k, s in zip(self.channels, kernels, strides):
            x = nn.Conv(ch, k, s, padding="VALID", dtype=self.compute_dtype,
                        param_dtype=self.param_dtype)(x)
            x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        v = nn.relu(nn.Dense(self.hidden, dtype=self.compute_dtype,
                             param_dtype=self.param_dtype)(x))
        a = nn.relu(nn.Dense(self.hidden, dtype=self.compute_dtype,
                             param_dtype=self.param_dtype)(x))
        value = nn.Dense(1, dtype=jnp.float32,
                         param_dtype=self.param_dtype)(v)
        advantage = nn.Dense(self.num_actions, dtype=jnp.float32,
                             param_dtype=self.param_dtype)(a)
        value = value.astype(jnp.float32)
        advantage = advantage.astype(jnp.float32)
        q = _dueling_aggregate(value, advantage)
        return DuelingOutput(value, advantage, q)

    def q_values(self, x: jax.Array) -> jax.Array:
        return self(x)[2]


class DuelingMLP(nn.Module):
    """Dueling Q-network for flat/vector observations (small envs, unit tests,
    chain-MDP learning tests — SURVEY §4 level 3)."""

    num_actions: int
    hidden_sizes: Sequence[int] = (256, 256)
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
        if x.dtype == jnp.uint8:
            x = x.astype(self.compute_dtype) / 255.0
        else:
            x = x.astype(self.compute_dtype)
        x = x.reshape((x.shape[0], -1))
        for h in self.hidden_sizes:
            x = nn.relu(nn.Dense(h, dtype=self.compute_dtype,
                                 param_dtype=self.param_dtype)(x))
        value = nn.Dense(1, dtype=jnp.float32, param_dtype=self.param_dtype)(x)
        advantage = nn.Dense(self.num_actions, dtype=jnp.float32,
                             param_dtype=self.param_dtype)(x)
        q = _dueling_aggregate(value.astype(jnp.float32), advantage.astype(jnp.float32))
        return DuelingOutput(value, advantage, q)

    def q_values(self, x: jax.Array) -> jax.Array:
        return self(x)[2]


def build_greedy_apply(network: nn.Module):
    """Jitted serving entry: ``(params, obs[B]) -> (actions[B], q[B, A])``.

    The inference twin of actors/pool.build_policy_step with the ε-greedy
    draw removed: pure greedy ``argmax Q(s, .)`` per row, no RNG threading —
    the compute kernel the serving batcher amortizes across clients
    (serving/batcher.py).  Q comes back float32 so clients can audit the
    argmax (tests pin padded-row independence through it).
    """

    @jax.jit
    def greedy_apply(params, obs):
        q = network.apply(params, obs)[2]
        return jnp.argmax(q, axis=-1).astype(jnp.int32), q

    return greedy_apply


def build_network(kind: str, num_actions: int, **kwargs) -> nn.Module:
    """Factory keyed by config string: {"conv", "nature", "mlp"}."""
    if kind == "conv":
        return DuelingDQN(num_actions=num_actions, **kwargs)
    if kind == "nature":
        kwargs.setdefault("channels", (32, 64, 64))
        return DuelingDQN(num_actions=num_actions, **kwargs)
    if kind == "mlp":
        return DuelingMLP(num_actions=num_actions, **kwargs)
    raise ValueError(f"unknown network kind: {kind}")
