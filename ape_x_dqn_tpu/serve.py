"""CLI serving mode: ``python -m ape_x_dqn_tpu.serve``.

Two mounting modes for the same PolicyServer (serving/server.py):

  * ``--checkpoint DIR`` — serve a trained Q-network from a checkpoint
    root, hot-reloading whenever a newer committed ``step_N`` lands
    (a training run writing checkpoints and a serving tier on the same
    filesystem need nothing else to stay current);
  * ``--attach`` — run the async trainer (runtime/async_pipeline.py) in
    this process and serve from its LIVE ParamStore: one process both
    trains and answers action requests, the learner's capped-rate publish
    doubling as the serving reload feed.

The server's client surface is in-process (``PolicyServer.act/submit`` —
tools/loadgen.py is the reference client); this CLI drives it with a
built-in closed-loop load (``--clients``) and emits the serving metrics
as JSONL (serve/qps, serve/p99_ms, serve/param_version, ...), so a config
can be sized — buckets, deadline, queue bound — before any transport
(HTTP/gRPC) is bolted on.
"""

from __future__ import annotations

import argparse
import sys
import threading
import time

from ape_x_dqn_tpu.config import load_config, to_dict
from ape_x_dqn_tpu.utils.metrics import MetricLogger


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ape_x_dqn_tpu.serve",
        description="Batched Q-network policy serving with hot param reload",
    )
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument(
        "--checkpoint", default=None, metavar="DIR",
        help="serve from this checkpoint root (hot-reloads newer steps)",
    )
    src.add_argument(
        "--attach", action="store_true",
        help="run the async trainer in-process and serve its live params",
    )
    p.add_argument(
        "--params-file", default=None,
        help="JSON config (native or reference format) — must match the "
        "checkpoint's network/env for --checkpoint",
    )
    p.add_argument(
        "--set", dest="overrides", action="append", default=[],
        metavar="PATH=VALUE",
        help="config override, e.g. --set serving.max_batch=64",
    )
    p.add_argument(
        "--duration", type=float, default=10.0,
        help="seconds to serve (--attach stops earlier if training ends)",
    )
    p.add_argument(
        "--clients", type=int, default=0,
        help="built-in closed-loop demo clients (0 = idle serve)",
    )
    p.add_argument(
        "--steps", type=int, default=None,
        help="--attach: learner steps to train (default: config total)",
    )
    p.add_argument("--metrics-file", default=None, help="also write JSONL here")
    p.add_argument("--metrics-every", type=float, default=2.0)
    p.add_argument(
        "--obs-port", type=int, default=None, metavar="PORT",
        help="start the /metrics + /varz + /healthz exporter on this port "
        "(0 = ephemeral; overrides config obs.export_port)",
    )
    return p


def _client_loop(server, obs_shape, stop, errors, seed):
    import numpy as np

    rng = np.random.default_rng(seed)
    while not stop.is_set():
        obs = rng.integers(0, 255, obs_shape, dtype=np.uint8)
        try:
            server.act(obs, timeout=30.0)
        except Exception:  # noqa: BLE001 — counted, loop continues
            errors.append(1)


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    cfg = load_config(args.params_file, overrides=args.overrides)
    print("serving config:", to_dict(cfg), file=sys.stderr)
    logger = MetricLogger(stream=sys.stdout, path=args.metrics_file)

    from ape_x_dqn_tpu.runtime.components import build_components
    from ape_x_dqn_tpu.serving import CheckpointParamSource, PolicyServer

    pipe = None
    trainer_thread = None
    if args.attach:
        # One process, both halves: the trainer owns the device hot loop,
        # the serving batcher rides the same device between learner
        # dispatches, params flow learner -> store -> server in host RAM.
        from ape_x_dqn_tpu.runtime import AsyncPipeline

        pipe = AsyncPipeline(cfg, logger=logger, log_every=10_000)
        comps = pipe.comps
        source = pipe.store
        trainer_thread = threading.Thread(
            target=lambda: pipe.run(learner_steps=args.steps),
            name="attached-trainer", daemon=True,
        )
    else:
        comps = build_components(cfg)
        source = CheckpointParamSource(args.checkpoint, comps.state)
        if source.version < 0:
            print(f"no checkpoint under {args.checkpoint}", file=sys.stderr)
            return 2

    s = cfg.serving
    server = PolicyServer(
        comps.network,
        param_source=source,
        max_batch=s.max_batch,
        max_wait_ms=s.max_wait_ms,
        queue_capacity=s.queue_capacity,
        reload_poll_s=s.reload_poll_s,
    )
    server.warmup(comps.obs_shape)
    server.start()

    # Serving staleness policy (runtime/supervisor): past
    # serving.param_stale_s of source silence the server sheds with the
    # typed ServerOverloaded and /healthz goes 503 — stale answers from a
    # dead source are a failure mode, not a feature.  Under --attach the
    # trainer's FleetSupervisor ticks it; standalone the metrics loop does.
    staleness = None
    if cfg.serving.param_stale_s > 0:
        if pipe is not None and pipe.supervisor is not None:
            staleness = pipe.supervisor.attach_serving(
                server, cfg.serving.param_stale_s
            )
        else:
            from ape_x_dqn_tpu.runtime.supervisor import (
                ServingStalenessPolicy,
            )

            staleness = ServingStalenessPolicy(
                server, cfg.serving.param_stale_s,
                on_event=lambda kind, **f: logger.event(kind, **f),
            )

    # Observability exporter over the serving tier (and, under --attach,
    # the trainer's registry too — one scrape covers both halves).
    obs_server = None
    obs_port = args.obs_port if args.obs_port is not None \
        else cfg.obs.export_port
    if obs_port is not None:
        from ape_x_dqn_tpu.obs import Health, MetricsRegistry, ObsServer

        if pipe is not None:
            registry, health = pipe.obs_registry, pipe.health
            pipe._close_obs()  # serve.py's exporter owns the port here
        else:
            registry = MetricsRegistry()
            health = Health(stale_after_s=cfg.obs.heartbeat_stale_s)
        registry.register_provider("serving", server.stats)
        health.register(
            "serving_batcher",
            lambda: time.monotonic() - server._batcher.heartbeat,
        )
        if staleness is not None:
            health.register(
                "serving_params", staleness.age_s,
                stale_after_s=cfg.serving.param_stale_s,
            )
        obs_server = ObsServer(registry, health, port=obs_port)
        logger.event("obs_exporter", port=obs_server.port,
                     url=obs_server.url)

    if trainer_thread is not None:
        trainer_thread.start()

    stop = threading.Event()
    errors: list = []
    clients = [
        threading.Thread(
            target=_client_loop,
            args=(server, comps.obs_shape, stop, errors, cfg.seed + i),
            name=f"serve-client-{i}", daemon=True,
        )
        for i in range(args.clients)
    ]
    for c in clients:
        c.start()
    try:
        deadline = time.monotonic() + args.duration
        while time.monotonic() < deadline:
            time.sleep(min(args.metrics_every, max(0.0, deadline - time.monotonic())))
            if staleness is not None:
                staleness.check()
            server.emit_metrics(logger)
            if trainer_thread is not None and not trainer_thread.is_alive():
                break
    finally:
        stop.set()
        for c in clients:
            c.join(timeout=5.0)
        if pipe is not None:
            pipe.stop_event.set()
        if trainer_thread is not None and trainer_thread.is_alive():
            trainer_thread.join(timeout=30.0)
        server.emit_metrics(logger, final=True)
        if obs_server is not None:
            obs_server.close()
        server.close()
        logger.close()
    return 0 if not errors else 1


if __name__ == "__main__":
    raise SystemExit(main())
