"""CLI serving mode: ``python -m ape_x_dqn_tpu.serve``.

Mounting modes for the same PolicyServer (serving/server.py), one per
param source:

  * ``--checkpoint DIR`` — serve a trained Q-network from a checkpoint
    root, hot-reloading whenever a newer committed ``step_N`` lands;
  * ``--attach`` — run the async trainer (runtime/async_pipeline.py) in
    this process and serve from its LIVE ParamStore;
  * ``--param-hub host:port:token:rid:attempt`` — REPLICA mode: subscribe
    to a fleet's param hub over a socket (serving/sources.py
    ``SocketParamSource``) — full snapshot on connect, page-deltas after;
  * ``--param-tail DIR`` — tail a ``ParamTailWriter`` APXC delta-chunk
    chain on a shared filesystem (the checkpoint-attached fallback:
    delta-sized files instead of full checkpoint re-reads).

Orthogonally, ``--listen [HOST:]PORT`` mounts the socket front end
(serving/net_server.py) over whichever server the mode built, announcing
the bound port as a ``serving_listen`` JSONL event (what the router and
the CI gates parse; port 0 = ephemeral).  ``--duration 0`` serves until
SIGTERM/SIGINT — how replicas run under a fleet.

``--replicas N`` is FLEET mode: spawn N replica subprocesses (each
``--listen <host>:0 --param-hub …``), route client connections to them
health-aware (serving/router.py), watch ``--checkpoint`` for new steps
and fan each one out to every replica as delta-or-full framed messages —
a hot reload reaches the whole fleet without any replica touching the
checkpoint dir.
"""

from __future__ import annotations

import argparse
import signal
import sys
import threading
import time

from ape_x_dqn_tpu.config import load_config, to_dict
from ape_x_dqn_tpu.utils.metrics import MetricLogger


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="ape_x_dqn_tpu.serve",
        description="Batched Q-network policy serving with hot param "
        "reload, a socket front end, and an N-replica routed fleet",
    )
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument(
        "--checkpoint", default=None, metavar="DIR",
        help="serve from this checkpoint root (hot-reloads newer steps); "
        "with --replicas: watch it and fan new steps out to the fleet",
    )
    src.add_argument(
        "--attach", action="store_true",
        help="run the async trainer in-process and serve its live params",
    )
    src.add_argument(
        "--param-hub", default=None, metavar="HOST:PORT:TOKEN:RID:ATTEMPT",
        help="replica mode: subscribe to a fleet param hub over a socket "
        "(delta-or-full framed updates; full snapshot on connect)",
    )
    src.add_argument(
        "--param-tail", default=None, metavar="DIR",
        help="tail a ParamTailWriter APXC delta-chunk chain in DIR",
    )
    p.add_argument(
        "--listen", default=None, metavar="[HOST:]PORT",
        help="serve the socket request/reply protocol here (0 = ephemeral; "
        "the bound port is announced as a serving_listen JSONL event)",
    )
    p.add_argument(
        "--replicas", type=int, default=None, metavar="N",
        help="fleet mode: N replica subprocesses behind the health-aware "
        "router (requires --checkpoint; 0 = serving.replicas default)",
    )
    p.add_argument(
        "--run-token", type=int, default=0, metavar="TOKEN",
        help="fleet-internal serving token: v2 hellos (central-inference "
        "workers) must carry it or are rejected at the handshake; 0 "
        "accepts any hello (anonymous front door)",
    )
    p.add_argument(
        "--params-file", default=None,
        help="JSON config (native or reference format) — must match the "
        "checkpoint's network/env for --checkpoint",
    )
    p.add_argument(
        "--set", dest="overrides", action="append", default=[],
        metavar="PATH=VALUE",
        help="config override, e.g. --set serving.max_batch=64",
    )
    p.add_argument(
        "--duration", type=float, default=10.0,
        help="seconds to serve; 0 = until SIGTERM/SIGINT (replica mode)",
    )
    p.add_argument(
        "--clients", type=int, default=0,
        help="built-in closed-loop demo clients (0 = idle serve)",
    )
    p.add_argument(
        "--steps", type=int, default=None,
        help="--attach: learner steps to train (default: config total)",
    )
    p.add_argument("--metrics-file", default=None, help="also write JSONL here")
    p.add_argument("--metrics-every", type=float, default=2.0)
    p.add_argument(
        "--obs-port", type=int, default=None, metavar="PORT",
        help="start the /metrics + /varz + /healthz exporter on this port "
        "(0 = ephemeral; overrides config obs.export_port)",
    )
    return p


def _parse_listen(spec: str, default_host: str):
    """``[HOST:]PORT`` → (host, port)."""
    if ":" in spec:
        host, port = spec.rsplit(":", 1)
        return host or default_host, int(port)
    return default_host, int(spec)


def _install_stop_handlers(stop: threading.Event) -> None:
    """SIGTERM/SIGINT → clean drain: the fleet stops replicas with
    SIGTERM, and a replica must close its sockets and flush its final
    metrics record instead of dying mid-frame."""

    def _handler(signum, frame):  # noqa: ARG001
        stop.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _handler)
        except (ValueError, OSError):
            pass  # non-main thread (tests drive main() directly)


def _client_loop(server, obs_shape, stop, errors, seed):
    import numpy as np

    rng = np.random.default_rng(seed)
    while not stop.is_set():
        obs = rng.integers(0, 255, obs_shape, dtype=np.uint8)
        try:
            server.act(obs, timeout=30.0)
        except Exception:  # noqa: BLE001 — counted, loop continues
            errors.append(1)


def _run_fleet(args, cfg, logger) -> int:
    """--replicas N: router + param hub + N replica children, watching
    the checkpoint dir and fanning new steps out as deltas."""
    from ape_x_dqn_tpu.runtime.components import build_components
    from ape_x_dqn_tpu.serving import CheckpointParamSource, ServingFleet

    if not args.checkpoint:
        print("--replicas requires --checkpoint (the fleet's param feed)",
              file=sys.stderr)
        return 2
    s = cfg.serving
    n = args.replicas if args.replicas and args.replicas > 0 else s.replicas
    comps = build_components(cfg)
    source = CheckpointParamSource(args.checkpoint, comps.state)
    got = source.get(-1)
    if got is None:
        print(f"no checkpoint under {args.checkpoint}", file=sys.stderr)
        return 2
    params, step = got

    host, port = (s.listen_host, s.listen_port)
    if args.listen is not None:
        host, port = _parse_listen(args.listen, s.listen_host)
    replica_args = []
    if args.params_file:
        replica_args += ["--params-file", args.params_file]
    for ov in args.overrides:
        replica_args += ["--set", ov]

    fleet = ServingFleet(
        replicas=n, listen_host=host, listen_port=port,
        probe_interval_s=s.probe_interval_s, replica_args=replica_args,
        on_event=lambda kind, **f: logger.event(kind, **f),
    )
    push = fleet.publish(params)
    logger.event("fleet_param_push", step=int(step), **push)
    try:
        fleet.start(timeout=s.replica_spawn_timeout_s)
    except Exception as e:  # noqa: BLE001 — spawn failure is terminal
        print(f"fleet start failed: {e}", file=sys.stderr)
        fleet.stop()
        return 3
    logger.event("serving_listen", port=fleet.port, host=host,
                 replicas=n, mode="router")

    obs_server = None
    obs_port = args.obs_port if args.obs_port is not None \
        else cfg.obs.export_port
    if obs_port is not None:
        from ape_x_dqn_tpu.obs import Health, MetricsRegistry, ObsServer

        registry = MetricsRegistry()
        health = Health(stale_after_s=cfg.obs.heartbeat_stale_s)
        registry.register_provider(
            "serving_router", fleet.router.stats
        )
        registry.register_provider("serving_fleet", fleet.stats)
        health.register(
            "router",
            lambda: 0.0 if fleet.router.stats()["healthy"] > 0 else 1e9,
            stale_after_s=1.0,
        )
        obs_server = ObsServer(registry, health, port=obs_port)
        logger.event("obs_exporter", port=obs_server.port,
                     url=obs_server.url)

    stop = threading.Event()
    _install_stop_handlers(stop)
    have_step = int(step)
    try:
        deadline = (time.monotonic() + args.duration
                    if args.duration > 0 else None)
        next_emit = time.monotonic() + args.metrics_every
        while not stop.is_set():
            if deadline is not None and time.monotonic() >= deadline:
                break
            # Poll the checkpoint dir at the reload cadence; emit a
            # metrics record at the (coarser) metrics cadence.
            stop.wait(min(args.metrics_every, cfg.serving.reload_poll_s))
            got = source.get(have_step)
            if got is not None:
                params, have_step = got[0], int(got[1])
                push = fleet.publish(params)
                logger.event("fleet_param_push", step=have_step, **push)
            if time.monotonic() >= next_emit:
                next_emit = time.monotonic() + args.metrics_every
                st = fleet.stats()
                logger.emit(serving_router=st["router"],
                            serving_fleet={k: st[k] for k in
                                           ("param", "respawns", "spawned",
                                            "retires", "retired",
                                            "param_version", "replicas")})
    finally:
        st = fleet.stats()
        logger.emit(serving_router=st["router"],
                    serving_fleet={k: st[k] for k in
                                   ("param", "respawns", "spawned",
                                    "retires", "retired", "param_version",
                                    "replicas")},
                    final=True)
        fleet.stop()
        if obs_server is not None:
            obs_server.close()
        logger.close()
    return 0


def main(argv=None) -> int:
    args = build_argparser().parse_args(argv)
    cfg = load_config(args.params_file, overrides=args.overrides)
    print("serving config:", to_dict(cfg), file=sys.stderr)
    logger = MetricLogger(stream=sys.stdout, path=args.metrics_file)

    if args.replicas is not None:
        return _run_fleet(args, cfg, logger)

    from ape_x_dqn_tpu.runtime.components import build_components
    from ape_x_dqn_tpu.serving import (
        CheckpointParamSource,
        ParamTailSource,
        PolicyServer,
        ServingNetServer,
        SocketParamSource,
    )

    pipe = None
    trainer_thread = None
    if args.attach:
        # One process, both halves: the trainer owns the device hot loop,
        # the serving batcher rides the same device between learner
        # dispatches, params flow learner -> store -> server in host RAM.
        from ape_x_dqn_tpu.runtime import AsyncPipeline

        pipe = AsyncPipeline(cfg, logger=logger, log_every=10_000)
        comps = pipe.comps
        source = pipe.store
        trainer_thread = threading.Thread(
            target=lambda: pipe.run(learner_steps=args.steps),
            name="attached-trainer", daemon=True,
        )
    else:
        comps = build_components(cfg)
        if args.param_hub:
            # Replica under a fleet: params arrive over the hub socket
            # (full on connect, deltas after) — no checkpoint dir here.
            source = SocketParamSource(args.param_hub, comps.state.params)
        elif args.param_tail:
            source = ParamTailSource(args.param_tail, comps.state.params)
            if source.version < 0:
                print(f"no param-tail chain under {args.param_tail}",
                      file=sys.stderr)
                return 2
        else:
            source = CheckpointParamSource(args.checkpoint, comps.state)
            if source.version < 0:
                print(f"no checkpoint under {args.checkpoint}",
                      file=sys.stderr)
                return 2

    s = cfg.serving
    server = PolicyServer(
        comps.network,
        param_source=source,
        max_batch=s.max_batch,
        max_wait_ms=s.max_wait_ms,
        queue_capacity=s.queue_capacity,
        reload_poll_s=s.reload_poll_s,
        # A replica may come up before its fleet's first publish reaches
        # it; give the socket source the spawn budget, not 30 s.
        source_timeout_s=(s.replica_spawn_timeout_s if args.param_hub
                          else 30.0),
        # Chaos: seeded per-batch service delay (the serving twin of the
        # slow-env injector — the autopilot smoke's disturbance source).
        apply_delay_ms=(cfg.chaos.serving_delay_ms
                        if cfg.chaos.enabled else 0.0),
        delay_seed=cfg.chaos.seed,
    )
    server.warmup(comps.obs_shape)
    server.start()

    # Socket front end: the request/reply plane over this server's
    # batcher.  The bound port is announced on the JSONL stream — the
    # router (fleet mode) and CI gates parse the serving_listen event.
    net_srv = None
    if args.listen is not None:
        host, port = _parse_listen(args.listen, s.listen_host)
        net_srv = ServingNetServer(
            server, host=host, port=port,
            max_request_bytes=s.max_request_bytes,
            run_token=args.run_token,
        ).start()
        server.attach_transport(net_srv.stats)
        logger.event("serving_listen", port=net_srv.port, host=host,
                     mode="replica")

    # Serving staleness policy (runtime/supervisor): past
    # serving.param_stale_s of source silence the server sheds with the
    # typed ServerOverloaded and /healthz goes 503 — stale answers from a
    # dead source are a failure mode, not a feature.  Under --attach the
    # trainer's FleetSupervisor ticks it; standalone the metrics loop does.
    staleness = None
    if cfg.serving.param_stale_s > 0:
        if pipe is not None and pipe.supervisor is not None:
            staleness = pipe.supervisor.attach_serving(
                server, cfg.serving.param_stale_s
            )
        else:
            from ape_x_dqn_tpu.runtime.supervisor import (
                ServingStalenessPolicy,
            )

            staleness = ServingStalenessPolicy(
                server, cfg.serving.param_stale_s,
                on_event=lambda kind, **f: logger.event(kind, **f),
            )

    # Observability exporter over the serving tier (and, under --attach,
    # the trainer's registry too — one scrape covers both halves).
    obs_server = None
    obs_port = args.obs_port if args.obs_port is not None \
        else cfg.obs.export_port
    if obs_port is not None:
        from ape_x_dqn_tpu.obs import Health, MetricsRegistry, ObsServer

        if pipe is not None:
            registry, health = pipe.obs_registry, pipe.health
            pipe._close_obs()  # serve.py's exporter owns the port here
        else:
            registry = MetricsRegistry()
            health = Health(stale_after_s=cfg.obs.heartbeat_stale_s)
        registry.register_provider("serving", server.stats)
        health.register(
            "serving_batcher",
            lambda: time.monotonic() - server.batcher.heartbeat,
        )
        if staleness is not None:
            health.register(
                "serving_params", staleness.age_s,
                stale_after_s=cfg.serving.param_stale_s,
            )
        obs_server = ObsServer(registry, health, port=obs_port)
        logger.event("obs_exporter", port=obs_server.port,
                     url=obs_server.url)

    if pipe is not None and net_srv is not None:
        # The attached trainer's periodic JSONL records carry the socket
        # plane as their own section (docs/METRICS.md `serving_net`).
        pipe.register_jsonl_section("serving_net", net_srv.stats)

    if trainer_thread is not None:
        trainer_thread.start()

    stop = threading.Event()
    _install_stop_handlers(stop)
    errors: list = []
    clients = [
        threading.Thread(
            target=_client_loop,
            args=(server, comps.obs_shape, stop, errors, cfg.seed + i),
            name=f"serve-client-{i}", daemon=True,
        )
        for i in range(args.clients)
    ]
    for c in clients:
        c.start()
    try:
        deadline = (time.monotonic() + args.duration
                    if args.duration > 0 else None)
        while not stop.is_set():
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                stop.wait(min(args.metrics_every, remaining))
            else:
                stop.wait(args.metrics_every)
            if staleness is not None:
                staleness.check()
            extra = {"serving_net": net_srv.stats()} if net_srv else {}
            server.emit_metrics(logger, **extra)
            if trainer_thread is not None and not trainer_thread.is_alive():
                break
    finally:
        stop.set()
        for c in clients:
            c.join(timeout=5.0)
        if pipe is not None:
            pipe.stop_event.set()
        if trainer_thread is not None and trainer_thread.is_alive():
            trainer_thread.join(timeout=30.0)
        if net_srv is not None:
            net_srv.close()
        extra = {"serving_net": net_srv.stats()} if net_srv else {}
        server.emit_metrics(logger, final=True, **extra)
        if obs_server is not None:
            obs_server.close()
        server.close()
        if hasattr(source, "close"):
            source.close()
        logger.close()
    return 0 if not errors else 1


if __name__ == "__main__":
    raise SystemExit(main())
