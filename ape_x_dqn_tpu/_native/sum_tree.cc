// Native sum-tree core for the prioritized replay.
//
// The reference has no native code at all (SURVEY §2: pure Python, the
// central replay is a flat dict with O(N) scans — reference replay.py:51-57).
// This C++ core is the new-work performance piece the north-star asks for:
// the central sum-tree is the only serialized component in Ape-X (SURVEY §7
// "hard parts" #1), so its set/sample throughput bounds learner steps/sec.
//
// C ABI (consumed via ctypes from ape_x_dqn_tpu/replay/native.py):
//   - flat array of 2*leaf_base float64 nodes, leaf i at leaf_base+i
//   - st_set:    batched leaf write + upward path propagation, last write wins
//   - st_sample: batched inverse-CDF descent (one branch per level per item)
//
// Build: g++ -O3 -shared -fPIC (driven by replay/native.py, cached .so).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

struct SumTree {
  int64_t capacity;
  int64_t leaf_base;  // power of two >= capacity
  std::vector<double> tree;  // size 2*leaf_base, tree[1] = total mass
};

int64_t next_pow2(int64_t n) {
  int64_t p = 1;
  while (p < n) p <<= 1;
  return p;
}

}  // namespace

extern "C" {

void* st_create(int64_t capacity) {
  if (capacity <= 0) return nullptr;
  auto* t = new SumTree();
  t->capacity = capacity;
  t->leaf_base = next_pow2(capacity);
  t->tree.assign(2 * t->leaf_base, 0.0);
  return t;
}

void st_destroy(void* handle) { delete static_cast<SumTree*>(handle); }

double st_total(void* handle) {
  return static_cast<SumTree*>(handle)->tree[1];
}

double st_max(void* handle) {
  auto* t = static_cast<SumTree*>(handle);
  double m = 0.0;
  for (int64_t i = 0; i < t->capacity; ++i) {
    double v = t->tree[t->leaf_base + i];
    if (v > m) m = v;
  }
  return m;
}

// Batched write: returns 0 on success, -1 on out-of-range index, -2 on a
// negative/non-finite priority.  Last write wins for duplicate indices
// (leaves written first, then each touched path re-summed bottom-up).
int32_t st_set(void* handle, int64_t n, const int64_t* indices,
               const double* priorities) {
  auto* t = static_cast<SumTree*>(handle);
  for (int64_t k = 0; k < n; ++k) {
    if (indices[k] < 0 || indices[k] >= t->capacity) return -1;
    if (!(priorities[k] >= 0.0) || priorities[k] != priorities[k]) return -2;
  }
  for (int64_t k = 0; k < n; ++k) {
    t->tree[t->leaf_base + indices[k]] = priorities[k];
  }
  // Propagate each touched path; parent = left + right is recomputed from
  // both children so duplicate indices cannot double-count.
  for (int64_t k = 0; k < n; ++k) {
    int64_t node = (t->leaf_base + indices[k]) >> 1;
    while (node >= 1) {
      t->tree[node] = t->tree[2 * node] + t->tree[2 * node + 1];
      node >>= 1;
    }
  }
  return 0;
}

void st_get(void* handle, int64_t n, const int64_t* indices, double* out) {
  auto* t = static_cast<SumTree*>(handle);
  for (int64_t k = 0; k < n; ++k) out[k] = t->tree[t->leaf_base + indices[k]];
}

// Batched inverse-CDF descent.  Targets must lie in [0, total); results are
// clamped to [0, capacity-1] against float round-off at interval edges.
void st_sample(void* handle, int64_t n, const double* targets, int64_t* out) {
  auto* t = static_cast<SumTree*>(handle);
  for (int64_t k = 0; k < n; ++k) {
    double target = targets[k];
    int64_t node = 1;
    while (node < t->leaf_base) {
      int64_t left = 2 * node;
      double left_mass = t->tree[left];
      if (target >= left_mass) {
        target -= left_mass;
        node = left + 1;
      } else {
        node = left;
      }
    }
    int64_t leaf = node - t->leaf_base;
    if (leaf >= t->capacity) leaf = t->capacity - 1;
    if (leaf < 0) leaf = 0;
    out[k] = leaf;
  }
}

}  // extern "C"
