// Native frame-dedup prioritized replay core — the paper-scale host path.
//
// Round-4 verdict item 1b: the pure-Python host replay measured ~4.3k
// sample+update pairs/s at 2M slots on this image's one core — below the
// single-chip fused learner rate, so config-scale host buffers could not
// feed the learner.  The costs are (a) Python call overhead per stage,
// (b) the frame gather's per-row fancy-indexing, (c) the sum-tree's
// ctypes round trips.  This core fuses each learner-facing operation into
// ONE C call (ctypes releases the GIL for the duration):
//
//   rc_add:    frame-ring write + transition write + priority set +
//              liveness sweep (obs_seq aged out -> mass 0), one pass;
//   rc_sample: stratified inverse-CDF descent + IS weights + BOTH frame
//              gathers (memcpy per row) into caller buffers;
//   rc_update: liveness-guarded priority restamp.
//
// The sum-tree is STRIPED K ways (slot i -> stripe i % K) with a mutex
// per stripe.  The striped sampling law matches the sharded device
// replay exactly — equal rows per stripe, proportional within,
// q_i = (m_i / M_s) / K — with the IS weights computed for that realized
// law (replay/device.py:137-145 is the same correction on TPU shards),
// so a run can move between host stripes and device shards without
// changing the estimator.  At n_stripes > 1 the Python wrapper fans each
// sample/update out as one rc_sample_stripe / rc_update_stripe call PER
// STRIPE through a persistent thread pool — ctypes releases the GIL, so
// the stripe calls genuinely overlap in wall-clock on multicore hosts
// (tests/test_native_dedup.py pins the overlap; the BENCH_r06 note about
// the wrapper serializing striped calls is fixed).  Add/import still
// serialize under the wrapper lock (carry-resolver state is Python-side).
// n_stripes=1 reduces bit-for-bit to the numpy DedupReplay (the oracle:
// tests/test_native_dedup.py).
//
// The frame ring is mmap'd with MADV_HUGEPAGE: a 2M x 7KB ring spans
// ~17 GB, and 4 KB TLB entries miss constantly under random gather; 2 MB
// transparent hugepages cut the page-walk tax (measured in BENCH host
// sections).
//
// Semantics contract (kept identical to replay/dedup.py — the Python
// wrapper replay/native_dedup.py shares the numpy twin's ref-resolution
// and tests pin parity): frame seqs are int64 (no wrap games host-side),
// obs_seq is each row's oldest ref, dead slots never resurrect.
//
// Build: g++ -O3 -shared -fPIC (replay/native_dedup.py, cached .so).

#include <sys/mman.h>
#include <sys/uio.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <vector>

namespace {

struct Stripe {
  int64_t leaf_base = 1;           // pow2 >= leaf count
  std::vector<double> tree;        // 2 * leaf_base nodes, tree[1] = total
  std::mutex mu;
};

struct Core {
  int64_t capacity = 0;            // transition slots
  int64_t frame_capacity = 0;      // frame slots
  int64_t frame_bytes = 0;         // bytes per frame
  double alpha = 0.6;
  int n_stripes = 1;

  uint8_t* frames = nullptr;       // mmap'd, frame_capacity * frame_bytes
  size_t frames_len = 0;
  std::vector<int64_t> obs_seq, next_seq;
  std::vector<int32_t> action;
  std::vector<float> reward, discount;
  std::vector<uint8_t> alive;

  int64_t cursor = 0;              // transition ring position
  int64_t count = 0;               // transitions ever accepted
  int64_t fcount = 0;              // frames ever written
  int64_t frame_dead = 0;          // sweep-invalidated rows (stat)
  std::vector<Stripe> stripes;
};

int64_t next_pow2(int64_t n) {
  int64_t p = 1;
  while (p < n) p <<= 1;
  return p;
}

// ---- striped sum-tree ------------------------------------------------

inline int stripe_of(const Core& c, int64_t slot) {
  return static_cast<int>(slot % c.n_stripes);
}
inline int64_t leaf_of(const Core& c, int64_t slot) {
  return slot / c.n_stripes;
}

void tree_set_one(Stripe& s, int64_t leaf, double v) {
  int64_t node = s.leaf_base + leaf;
  s.tree[node] = v;
  for (node >>= 1; node >= 1; node >>= 1)
    s.tree[node] = s.tree[2 * node] + s.tree[2 * node + 1];
}

int64_t tree_descend(const Stripe& s, double target) {
  int64_t node = 1;
  while (node < s.leaf_base) {
    double left = s.tree[2 * node];
    if (target < left) {
      node = 2 * node;
    } else {
      target -= left;
      node = 2 * node + 1;
    }
  }
  return node - s.leaf_base;
}

}  // namespace

extern "C" {

void* rc_create(int64_t capacity, int64_t frame_capacity,
                int64_t frame_bytes, double alpha, int n_stripes) {
  if (capacity <= 0 || frame_capacity <= 0 || frame_bytes <= 0 ||
      n_stripes <= 0)
    return nullptr;
  Core* c = new (std::nothrow) Core();
  if (!c) return nullptr;
  c->capacity = capacity;
  c->frame_capacity = frame_capacity;
  c->frame_bytes = frame_bytes;
  c->alpha = alpha;
  c->n_stripes = n_stripes;
  c->frames_len = static_cast<size_t>(frame_capacity) * frame_bytes;
  void* mem = mmap(nullptr, c->frames_len, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
  if (mem == MAP_FAILED) {
    delete c;
    return nullptr;
  }
  // 2 MB transparent hugepages for the gather-heavy frame ring.
  madvise(mem, c->frames_len, MADV_HUGEPAGE);
  c->frames = static_cast<uint8_t*>(mem);
  c->obs_seq.assign(capacity, 0);
  c->next_seq.assign(capacity, 0);
  c->action.assign(capacity, 0);
  c->reward.assign(capacity, 0.f);
  c->discount.assign(capacity, 0.f);
  c->alive.assign(capacity, 0);
  c->stripes = std::vector<Stripe>(n_stripes);
  for (int s = 0; s < n_stripes; ++s) {
    int64_t leaves = (capacity - s + n_stripes - 1) / n_stripes;
    c->stripes[s].leaf_base = next_pow2(std::max<int64_t>(leaves, 1));
    c->stripes[s].tree.assign(2 * c->stripes[s].leaf_base, 0.0);
  }
  return c;
}

void rc_destroy(void* h) {
  Core* c = static_cast<Core*>(h);
  if (!c) return;
  if (c->frames) munmap(c->frames, c->frames_len);
  delete c;
}

int64_t rc_size(void* h) {
  Core* c = static_cast<Core*>(h);
  return std::min(c->count, c->capacity);
}
int64_t rc_count(void* h) { return static_cast<Core*>(h)->count; }
int64_t rc_fcount(void* h) { return static_cast<Core*>(h)->fcount; }
int64_t rc_cursor(void* h) { return static_cast<Core*>(h)->cursor; }
int64_t rc_frame_dead(void* h) { return static_cast<Core*>(h)->frame_dead; }

double rc_total(void* h) {
  Core* c = static_cast<Core*>(h);
  double t = 0;
  for (auto& s : c->stripes) t += s.tree[1];
  return t;
}

double rc_max(void* h) {
  Core* c = static_cast<Core*>(h);
  double m = 0;
  for (auto& s : c->stripes)
    for (int64_t i = s.leaf_base; i < 2 * s.leaf_base; ++i)
      m = std::max(m, s.tree[i]);
  return m;
}

// Ingest one chunk: U frames + M transitions with pre-resolved absolute
// refs, then the liveness sweep.  Returns the first transition slot
// written (ring order), or -1 on a size violation.
int64_t rc_add(void* h, int64_t U, const uint8_t* frames, int64_t M,
               const int64_t* obs_seq, const int64_t* next_seq,
               const int32_t* action, const float* reward,
               const float* discount, const float* prio) {
  Core* c = static_cast<Core*>(h);
  if (U > c->frame_capacity || M > c->capacity) return -1;
  // Frame-ring write (seq-addressed slots; U <= Cf so at most one wrap).
  int64_t fslot = c->fcount % c->frame_capacity;
  int64_t first = std::min(U, c->frame_capacity - fslot);
  std::memcpy(c->frames + fslot * c->frame_bytes, frames,
              static_cast<size_t>(first) * c->frame_bytes);
  if (first < U)
    std::memcpy(c->frames, frames + first * c->frame_bytes,
                static_cast<size_t>(U - first) * c->frame_bytes);
  c->fcount += U;
  // Transition ring write + priority set (stripe-locked per row batch).
  int64_t base = c->cursor;
  for (int64_t i = 0; i < M; ++i) {
    int64_t slot = (base + i) % c->capacity;
    c->obs_seq[slot] = obs_seq[i];
    c->next_seq[slot] = next_seq[i];
    c->action[slot] = action[i];
    c->reward[slot] = reward[i];
    c->discount[slot] = discount[i];
    c->alive[slot] = 1;
    double p = std::pow(std::max(static_cast<double>(prio[i]), 1e-12),
                        c->alpha);
    Stripe& s = c->stripes[stripe_of(*c, slot)];
    std::lock_guard<std::mutex> g(s.mu);
    tree_set_one(s, leaf_of(*c, slot), p);
  }
  c->cursor = (base + M) % c->capacity;
  c->count += M;
  // Liveness sweep: rows whose obs frame was overwritten lose their mass.
  int64_t fmin = c->fcount - c->frame_capacity;
  if (fmin > 0) {
    int64_t size = std::min(c->count, c->capacity);
    for (int64_t slot = 0; slot < size; ++slot) {
      if (c->alive[slot] && c->obs_seq[slot] < fmin) {
        c->alive[slot] = 0;
        ++c->frame_dead;
        Stripe& s = c->stripes[stripe_of(*c, slot)];
        std::lock_guard<std::mutex> g(s.mu);
        tree_set_one(s, leaf_of(*c, slot), 0.0);
      }
    }
  }
  return base;
}

// Stratified PER sample: B rows (B % n_stripes == 0; B/K per stripe, the
// striped law), gathering both frames and computing IS weights in one
// GIL-released call.  `u` supplies B uniforms (RNG stays in Python so the
// numpy twin is a bit-exact oracle at n_stripes=1).
// Returns 0 ok, -1 empty, -2 B not divisible by stripes.
int32_t rc_sample(void* h, int64_t B, double beta, const double* u,
                  int64_t* out_idx, double* out_weights, uint8_t* out_obs,
                  uint8_t* out_next, int32_t* out_action, float* out_reward,
                  float* out_discount) {
  Core* c = static_cast<Core*>(h);
  if (B % c->n_stripes) return -2;
  int64_t size = std::min(c->count, c->capacity);
  if (size == 0) return -1;
  int64_t Bk = B / c->n_stripes;
  double wmax = 0.0;
  for (int s_i = 0; s_i < c->n_stripes; ++s_i) {
    Stripe& s = c->stripes[s_i];
    std::lock_guard<std::mutex> g(s.mu);
    double total = s.tree[1];
    if (total <= 0) return -1;  // a populated core never has an empty stripe
    double bounds = total / Bk;
    double clip = std::nextafter(total, 0.0);
    for (int64_t j = 0; j < Bk; ++j) {
      double target = (j + u[s_i * Bk + j]) * bounds;
      target = std::min(std::max(target, 0.0), clip);
      int64_t leaf = tree_descend(s, target);
      int64_t slot = leaf * c->n_stripes + s_i;
      if (slot >= c->capacity) slot = c->capacity - 1 - ((c->capacity - 1 - s_i) % c->n_stripes);
      int64_t k = s_i * Bk + j;
      out_idx[k] = slot;
      double mass = s.tree[s.leaf_base + leaf_of(*c, slot)];
      // Realized law: equal rows per stripe, proportional within —
      // q = (mass / total_s) / K; w = (N * q)^-beta.  The guard sits on
      // the within-stripe probability so n_stripes=1 is BIT-exact with
      // the numpy twin's size * max(probs, 1e-12) spelling.
      double q0 = std::max(mass / total, 1e-12);
      double w = std::pow(static_cast<double>(size) * q0 / c->n_stripes,
                          -beta);
      out_weights[k] = w;
      if (w > wmax) wmax = w;
    }
  }
  for (int64_t k = 0; k < B; ++k) {
    out_weights[k] /= wmax;
    int64_t slot = out_idx[k];
    int64_t of = c->obs_seq[slot] % c->frame_capacity;
    int64_t nf = c->next_seq[slot] % c->frame_capacity;
    std::memcpy(out_obs + k * c->frame_bytes,
                c->frames + of * c->frame_bytes, c->frame_bytes);
    std::memcpy(out_next + k * c->frame_bytes,
                c->frames + nf * c->frame_bytes, c->frame_bytes);
    out_action[k] = c->action[slot];
    out_reward[k] = c->reward[slot];
    out_discount[k] = c->discount[slot];
  }
  return 0;
}

// Liveness-guarded priority restamp (last write wins within the batch).
void rc_update(void* h, int64_t n, const int64_t* idx, const float* prio) {
  Core* c = static_cast<Core*>(h);
  int64_t fmin = c->fcount - c->frame_capacity;
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = idx[i];
    if (slot < 0 || slot >= c->capacity) continue;
    if (!c->alive[slot] || c->obs_seq[slot] < fmin) continue;
    double p = std::pow(std::max(static_cast<double>(prio[i]), 1e-12),
                        c->alpha);
    Stripe& s = c->stripes[stripe_of(*c, slot)];
    std::lock_guard<std::mutex> g(s.mu);
    tree_set_one(s, leaf_of(*c, slot), p);
  }
}

// Per-stripe half of rc_sample, for the wrapper's PARALLEL fan-out
// (replay/native_dedup.py dispatches one call per stripe through a
// persistent thread pool; ctypes releases the GIL so stripe calls overlap
// in wall-clock — the BENCH_r06 "striped4 wrapper serializes calls"
// defect, fixed).  Samples Bk rows from stripe `s_i` using u[0..Bk) and
// writes RAW (unnormalized) IS weights — the caller normalizes by the max
// across ALL stripes, reproducing rc_sample's arithmetic bit-for-bit.
// The gather runs outside the stripe lock, like rc_sample's (the Python
// wrapper's lock excludes add/import during sampling).
// Returns 0 ok, -1 empty stripe, -3 bad stripe id.
int32_t rc_sample_stripe(void* h, int32_t s_i, int64_t Bk, double beta,
                         const double* u, int64_t* out_idx,
                         double* out_weights, uint8_t* out_obs,
                         uint8_t* out_next, int32_t* out_action,
                         float* out_reward, float* out_discount) {
  Core* c = static_cast<Core*>(h);
  if (s_i < 0 || s_i >= c->n_stripes) return -3;
  int64_t size = std::min(c->count, c->capacity);
  if (size == 0) return -1;
  Stripe& s = c->stripes[s_i];
  {
    std::lock_guard<std::mutex> g(s.mu);
    double total = s.tree[1];
    if (total <= 0) return -1;
    double bounds = total / Bk;
    double clip = std::nextafter(total, 0.0);
    for (int64_t j = 0; j < Bk; ++j) {
      double target = (j + u[j]) * bounds;
      target = std::min(std::max(target, 0.0), clip);
      int64_t leaf = tree_descend(s, target);
      int64_t slot = leaf * c->n_stripes + s_i;
      if (slot >= c->capacity)
        slot = c->capacity - 1 - ((c->capacity - 1 - s_i) % c->n_stripes);
      out_idx[j] = slot;
      double mass = s.tree[s.leaf_base + leaf_of(*c, slot)];
      double q0 = std::max(mass / total, 1e-12);
      out_weights[j] = std::pow(static_cast<double>(size) * q0 /
                                    c->n_stripes,
                                -beta);
    }
  }
  for (int64_t j = 0; j < Bk; ++j) {
    int64_t slot = out_idx[j];
    int64_t of = c->obs_seq[slot] % c->frame_capacity;
    int64_t nf = c->next_seq[slot] % c->frame_capacity;
    std::memcpy(out_obs + j * c->frame_bytes,
                c->frames + of * c->frame_bytes, c->frame_bytes);
    std::memcpy(out_next + j * c->frame_bytes,
                c->frames + nf * c->frame_bytes, c->frame_bytes);
    out_action[j] = c->action[slot];
    out_reward[j] = c->reward[slot];
    out_discount[j] = c->discount[slot];
  }
  return 0;
}

// Per-stripe half of rc_update: scans the full batch but touches only the
// slots belonging to `s_i` — each pool worker owns one stripe's tree, so
// the fan-out has zero cross-stripe lock contention and preserves
// rc_update's in-order last-write-wins within the stripe.
void rc_update_stripe(void* h, int32_t s_i, int64_t n, const int64_t* idx,
                      const float* prio) {
  Core* c = static_cast<Core*>(h);
  if (s_i < 0 || s_i >= c->n_stripes) return;
  int64_t fmin = c->fcount - c->frame_capacity;
  Stripe& s = c->stripes[s_i];
  std::lock_guard<std::mutex> g(s.mu);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = idx[i];
    if (slot < 0 || slot >= c->capacity) continue;
    if (stripe_of(*c, slot) != s_i) continue;
    if (!c->alive[slot] || c->obs_seq[slot] < fmin) continue;
    double p = std::pow(std::max(static_cast<double>(prio[i]), 1e-12),
                        c->alpha);
    tree_set_one(s, leaf_of(*c, slot), p);
  }
}

// ---- tiered frame store (replay/tiered.py SpanTierIndex) -------------
// The cold tier keeps the frame mmap address-stable and moves BYTES only:
// rc_evict_span copies a span out for the python-side cold write and
// MADV_DONTNEEDs its pages (RSS released, reads become zero-fill);
// rc_fault_span copies verified cold bytes back in.  Sampling splits in
// two GIL-released calls — rc_sample_idx (descent + weights + metadata,
// bit-identical law to rc_sample) so the wrapper can fault the spans the
// batch actually needs, then rc_gather_frames for the two frame gathers.

namespace {

// zlib-compatible CRC-32 (reflected 0xEDB88320), slice-by-8 — the fault
// batch verifies ~60 KB spans at memory speed instead of paying
// python-side zlib calls per span.
uint32_t crc_tab[8][256];
bool crc_ready = false;

void crc_init() {
  if (crc_ready) return;
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t x = i;
    for (int k = 0; k < 8; ++k)
      x = (x & 1) ? 0xEDB88320u ^ (x >> 1) : x >> 1;
    crc_tab[0][i] = x;
  }
  for (uint32_t i = 0; i < 256; ++i)
    for (int s = 1; s < 8; ++s)
      crc_tab[s][i] =
          (crc_tab[s - 1][i] >> 8) ^ crc_tab[0][crc_tab[s - 1][i] & 0xFF];
  crc_ready = true;
}

uint32_t crc32z(const uint8_t* p, size_t n) {
  crc_init();
  uint32_t crc = 0xFFFFFFFFu;
  while (n && (reinterpret_cast<uintptr_t>(p) & 7)) {
    crc = crc_tab[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
    --n;
  }
  while (n >= 8) {
    uint32_t lo, hi;
    std::memcpy(&lo, p, 4);
    std::memcpy(&hi, p + 4, 4);
    crc ^= lo;
    crc = crc_tab[7][crc & 0xFF] ^ crc_tab[6][(crc >> 8) & 0xFF] ^
          crc_tab[5][(crc >> 16) & 0xFF] ^ crc_tab[4][crc >> 24] ^
          crc_tab[3][hi & 0xFF] ^ crc_tab[2][(hi >> 8) & 0xFF] ^
          crc_tab[1][(hi >> 16) & 0xFF] ^ crc_tab[0][hi >> 24];
    p += 8;
    n -= 8;
  }
  while (n--) crc = crc_tab[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

void drop_pages(Core* c, int64_t slot, int64_t n) {
  static const uintptr_t page = 4096;
  uint8_t* lo = c->frames + slot * c->frame_bytes;
  uint8_t* hi = lo + n * c->frame_bytes;
  uint8_t* alo = reinterpret_cast<uint8_t*>(
      (reinterpret_cast<uintptr_t>(lo) + page - 1) & ~(page - 1));
  uint8_t* ahi = reinterpret_cast<uint8_t*>(
      reinterpret_cast<uintptr_t>(hi) & ~(page - 1));
  // Inner-aligned only: edge pages shared with neighbor spans keep their
  // bytes (the copy-out above covered this span's own content).
  if (ahi > alo) madvise(alo, ahi - alo, MADV_DONTNEED);
}
}  // namespace

// Copy n frame slots starting at ring slot fstart (wrap-aware) into out,
// then release the copied region's pages back to the OS.  The span's
// content lives only in the caller's buffer afterwards — write it to the
// cold store before dropping the reference.
void rc_evict_span(void* h, int64_t fstart, int64_t n, uint8_t* out) {
  Core* c = static_cast<Core*>(h);
  int64_t slot = fstart % c->frame_capacity;
  int64_t first = std::min(n, c->frame_capacity - slot);
  std::memcpy(out, c->frames + slot * c->frame_bytes,
              static_cast<size_t>(first) * c->frame_bytes);
  drop_pages(c, slot, first);
  if (first < n) {
    std::memcpy(out + first * c->frame_bytes, c->frames,
                static_cast<size_t>(n - first) * c->frame_bytes);
    drop_pages(c, 0, n - first);
  }
}

// Copy verified cold bytes back into the ring (the fault half).  Body is
// rc_import_frames_span's; the separate export names the tier contract.
void rc_fault_span(void* h, int64_t fstart, int64_t n,
                   const uint8_t* frames) {
  Core* c = static_cast<Core*>(h);
  int64_t slot = fstart % c->frame_capacity;
  int64_t first = std::min(n, c->frame_capacity - slot);
  std::memcpy(c->frames + slot * c->frame_bytes, frames,
              static_cast<size_t>(first) * c->frame_bytes);
  if (first < n)
    std::memcpy(c->frames, frames + first * c->frame_bytes,
                static_cast<size_t>(n - first) * c->frame_bytes);
}

// Tiered rings opt OUT of transparent hugepages: the eviction cycle
// MADV_DONTNEEDs sub-hugepage ranges, and every such drop on a THP
// region splits a 2 MB page (measured ~10x the cost of a 4 KB-page
// drop) — the gather's TLB win is repaid many times over in page-table
// surgery.  Called once by the wrapper when a tier is attached.
void rc_nohugepage(void* h) {
  Core* c = static_cast<Core*>(h);
  madvise(c->frames, c->frames_len, MADV_NOHUGEPAGE);
}

// Release a span's pages WITHOUT copying it out first — the clean-drop
// eviction (disk record already current; rc_evict_span's copy would be
// wasted work on the evictor thread).
void rc_drop_span(void* h, int64_t fstart, int64_t n) {
  Core* c = static_cast<Core*>(h);
  int64_t slot = fstart % c->frame_capacity;
  int64_t first = std::min(n, c->frame_capacity - slot);
  drop_pages(c, slot, first);
  if (first < n) drop_pages(c, 0, n - first);
}

// Batched cold fault, entirely GIL-released: for each span, pread the
// record at `offsets[i]` from the spill file's fd straight into the ring
// (span regions are span-aligned, so they never wrap), then verify
// framing + self-CRC + the caller's expected content CRC over the landed
// bytes.  Returns -1 when every span verified, else the index of the
// first failing span (its ring bytes may be partial, but the caller only
// marks spans resident on success, so a failed fault is retried — and
// fails typed — on the next access).  Record layout must match
// replay/tiered.py ColdSpanStore: "APXS" | u32 version | u64 span_id |
// u64 payload_len | u32 crc32.
int64_t rc_fault_batch(void* h, int32_t fd, int64_t n,
                       const int64_t* offsets, const int64_t* fstarts,
                       const int64_t* nframes, const int64_t* span_ids,
                       const int64_t* want_crcs) {
  Core* c = static_cast<Core*>(h);
  uint8_t hdr[28];
  for (int64_t i = 0; i < n; ++i) {
    uint64_t want_len = static_cast<uint64_t>(nframes[i]) * c->frame_bytes;
    uint8_t* dst =
        c->frames + (fstarts[i] % c->frame_capacity) * c->frame_bytes;
    // One syscall per span: header scatters into hdr, payload lands
    // straight in the ring (span regions are span-aligned — no wrap).
    struct iovec iov[2];
    iov[0].iov_base = hdr;
    iov[0].iov_len = 28;
    iov[1].iov_base = dst;
    iov[1].iov_len = want_len;
    if (preadv(fd, iov, 2, offsets[i]) !=
        static_cast<ssize_t>(28 + want_len))
      return i;
    if (std::memcmp(hdr, "APXS", 4) != 0) return i;
    uint32_t version, crc;
    uint64_t sid, plen;
    std::memcpy(&version, hdr + 4, 4);
    std::memcpy(&sid, hdr + 8, 8);
    std::memcpy(&plen, hdr + 16, 8);
    std::memcpy(&crc, hdr + 24, 4);
    if (version != 1) return i;
    if (static_cast<int64_t>(sid) != span_ids[i]) return i;
    if (plen != want_len) return i;
    uint32_t actual = crc32z(dst, plen);
    if (actual != crc) return i;
    if (want_crcs[i] >= 0 && actual != static_cast<uint32_t>(want_crcs[i]))
      return i;
  }
  return -1;
}

// rc_sample minus the frame memcpys, plus each row's frame seqs so the
// wrapper knows which spans to fault.  Same striped descent, same
// uniforms, same weight arithmetic — rc_sample_idx + rc_gather_frames on
// an all-hot ring is bit-identical to one rc_sample call (tests pin it).
int32_t rc_sample_idx(void* h, int64_t B, double beta, const double* u,
                      int64_t* out_idx, double* out_weights,
                      int64_t* out_obs_seq, int64_t* out_next_seq,
                      int32_t* out_action, float* out_reward,
                      float* out_discount) {
  Core* c = static_cast<Core*>(h);
  if (B % c->n_stripes) return -2;
  int64_t size = std::min(c->count, c->capacity);
  if (size == 0) return -1;
  int64_t Bk = B / c->n_stripes;
  double wmax = 0.0;
  for (int s_i = 0; s_i < c->n_stripes; ++s_i) {
    Stripe& s = c->stripes[s_i];
    std::lock_guard<std::mutex> g(s.mu);
    double total = s.tree[1];
    if (total <= 0) return -1;
    double bounds = total / Bk;
    double clip = std::nextafter(total, 0.0);
    for (int64_t j = 0; j < Bk; ++j) {
      double target = (j + u[s_i * Bk + j]) * bounds;
      target = std::min(std::max(target, 0.0), clip);
      int64_t leaf = tree_descend(s, target);
      int64_t slot = leaf * c->n_stripes + s_i;
      if (slot >= c->capacity)
        slot = c->capacity - 1 - ((c->capacity - 1 - s_i) % c->n_stripes);
      int64_t k = s_i * Bk + j;
      out_idx[k] = slot;
      double mass = s.tree[s.leaf_base + leaf_of(*c, slot)];
      double q0 = std::max(mass / total, 1e-12);
      double w = std::pow(static_cast<double>(size) * q0 / c->n_stripes,
                          -beta);
      out_weights[k] = w;
      if (w > wmax) wmax = w;
    }
  }
  for (int64_t k = 0; k < B; ++k) {
    out_weights[k] /= wmax;
    int64_t slot = out_idx[k];
    out_obs_seq[k] = c->obs_seq[slot];
    out_next_seq[k] = c->next_seq[slot];
    out_action[k] = c->action[slot];
    out_reward[k] = c->reward[slot];
    out_discount[k] = c->discount[slot];
  }
  return 0;
}

// Second half of the two-phase sample: both frame gathers for the given
// transition slots (the wrapper faulted their spans hot first).
void rc_gather_frames(void* h, int64_t B, const int64_t* idx,
                      uint8_t* out_obs, uint8_t* out_next) {
  Core* c = static_cast<Core*>(h);
  for (int64_t k = 0; k < B; ++k) {
    int64_t slot = idx[k];
    int64_t of = c->obs_seq[slot] % c->frame_capacity;
    int64_t nf = c->next_seq[slot] % c->frame_capacity;
    std::memcpy(out_obs + k * c->frame_bytes,
                c->frames + of * c->frame_bytes, c->frame_bytes);
    std::memcpy(out_next + k * c->frame_bytes,
                c->frames + nf * c->frame_bytes, c->frame_bytes);
  }
}

double rc_get_mass(void* h, int64_t slot) {
  Core* c = static_cast<Core*>(h);
  if (slot < 0 || slot >= c->capacity) return -1.0;
  Stripe& s = c->stripes[stripe_of(*c, slot)];
  return s.tree[s.leaf_base + leaf_of(*c, slot)];
}

// ---- snapshot (checkpointing) ---------------------------------------

// Copy state into caller-provided buffers sized by the counters above:
// frames [min(fcount, Cf) * frame_bytes] slot-ordered, per-slot arrays
// [size], masses [size].
void rc_export(void* h, uint8_t* frames, int64_t* obs_seq,
               int64_t* next_seq, int32_t* action, float* reward,
               float* discount, uint8_t* alive, double* mass) {
  Core* c = static_cast<Core*>(h);
  int64_t nf = std::min(c->fcount, c->frame_capacity);
  std::memcpy(frames, c->frames, static_cast<size_t>(nf) * c->frame_bytes);
  int64_t size = std::min(c->count, c->capacity);
  std::memcpy(obs_seq, c->obs_seq.data(), size * sizeof(int64_t));
  std::memcpy(next_seq, c->next_seq.data(), size * sizeof(int64_t));
  std::memcpy(action, c->action.data(), size * sizeof(int32_t));
  std::memcpy(reward, c->reward.data(), size * sizeof(float));
  std::memcpy(discount, c->discount.data(), size * sizeof(float));
  std::memcpy(alive, c->alive.data(), size * sizeof(uint8_t));
  for (int64_t slot = 0; slot < size; ++slot)
    mass[slot] = rc_get_mass(h, slot);
}

// Restore from a snapshot (sizes must match the live core's config).
// Returns 0 ok, -1 on size violation.
int32_t rc_import(void* h, int64_t nf, const uint8_t* frames, int64_t size,
                  const int64_t* obs_seq, const int64_t* next_seq,
                  const int32_t* action, const float* reward,
                  const float* discount, const uint8_t* alive,
                  const double* mass, int64_t cursor, int64_t count,
                  int64_t fcount) {
  Core* c = static_cast<Core*>(h);
  if (nf > c->frame_capacity || size > c->capacity) return -1;
  std::memcpy(c->frames, frames, static_cast<size_t>(nf) * c->frame_bytes);
  for (auto& s : c->stripes)
    std::fill(s.tree.begin(), s.tree.end(), 0.0);
  std::fill(c->alive.begin(), c->alive.end(), 0);
  std::memcpy(c->obs_seq.data(), obs_seq, size * sizeof(int64_t));
  std::memcpy(c->next_seq.data(), next_seq, size * sizeof(int64_t));
  std::memcpy(c->action.data(), action, size * sizeof(int32_t));
  std::memcpy(c->reward.data(), reward, size * sizeof(float));
  std::memcpy(c->discount.data(), discount, size * sizeof(float));
  std::memcpy(c->alive.data(), alive, size * sizeof(uint8_t));
  for (int64_t slot = 0; slot < size; ++slot) {
    Stripe& s = c->stripes[stripe_of(*c, slot)];
    tree_set_one(s, leaf_of(*c, slot), mass[slot]);
  }
  c->cursor = cursor % c->capacity;
  c->count = count;
  c->fcount = fcount;
  return 0;
}

// ---- incremental snapshot (dirty spans + sparse; utils/checkpoint_inc) --
// The rings write sequentially at cursors, so a delta is the frame span +
// transition span written since the last snapshot plus the sparse slots
// whose priority/liveness changed.  These exports/imports are the C-core
// halves of NativeDedupReplay.delta_state_dict / apply_delta_state_dict;
// row order matches the python twin's fancy-indexed spans exactly.

// Full liveness vector [capacity] — the wrapper diffs it against the
// previous snapshot's copy to find sweep-invalidated slots (the sweep
// runs inside rc_add, so python never sees the indices directly).
void rc_export_alive(void* h, uint8_t* out) {
  Core* c = static_cast<Core*>(h);
  std::memcpy(out, c->alive.data(), static_cast<size_t>(c->capacity));
}

// Wrap-aware copy of n frame slots starting at seq fstart (n <= Cf).
void rc_export_frames_span(void* h, int64_t fstart, int64_t n,
                           uint8_t* out) {
  Core* c = static_cast<Core*>(h);
  int64_t slot = fstart % c->frame_capacity;
  int64_t first = std::min(n, c->frame_capacity - slot);
  std::memcpy(out, c->frames + slot * c->frame_bytes,
              static_cast<size_t>(first) * c->frame_bytes);
  if (first < n)
    std::memcpy(out + first * c->frame_bytes, c->frames,
                static_cast<size_t>(n - first) * c->frame_bytes);
}

void rc_import_frames_span(void* h, int64_t fstart, int64_t n,
                           const uint8_t* frames) {
  Core* c = static_cast<Core*>(h);
  int64_t slot = fstart % c->frame_capacity;
  int64_t first = std::min(n, c->frame_capacity - slot);
  std::memcpy(c->frames + slot * c->frame_bytes, frames,
              static_cast<size_t>(first) * c->frame_bytes);
  if (first < n)
    std::memcpy(c->frames, frames + first * c->frame_bytes,
                static_cast<size_t>(n - first) * c->frame_bytes);
}

// n transition rows from ring slot `start` (wrap-aware), with liveness
// and tree mass — the full dirty span of one delta.
void rc_export_rows(void* h, int64_t start, int64_t n, int64_t* obs_seq,
                    int64_t* next_seq, int32_t* action, float* reward,
                    float* discount, uint8_t* alive, double* mass) {
  Core* c = static_cast<Core*>(h);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = (start + i) % c->capacity;
    obs_seq[i] = c->obs_seq[slot];
    next_seq[i] = c->next_seq[slot];
    action[i] = c->action[slot];
    reward[i] = c->reward[slot];
    discount[i] = c->discount[slot];
    alive[i] = c->alive[slot];
    Stripe& s = c->stripes[stripe_of(*c, slot)];
    mass[i] = s.tree[s.leaf_base + leaf_of(*c, slot)];
  }
}

void rc_import_rows(void* h, int64_t start, int64_t n,
                    const int64_t* obs_seq, const int64_t* next_seq,
                    const int32_t* action, const float* reward,
                    const float* discount, const uint8_t* alive,
                    const double* mass) {
  Core* c = static_cast<Core*>(h);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = (start + i) % c->capacity;
    c->obs_seq[slot] = obs_seq[i];
    c->next_seq[slot] = next_seq[i];
    c->action[slot] = action[i];
    c->reward[slot] = reward[i];
    c->discount[slot] = discount[i];
    c->alive[slot] = alive[i];
    Stripe& s = c->stripes[stripe_of(*c, slot)];
    tree_set_one(s, leaf_of(*c, slot), mass[i]);
  }
}

void rc_export_mass(void* h, int64_t n, const int64_t* idx, double* out) {
  Core* c = static_cast<Core*>(h);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = idx[i];
    if (slot < 0 || slot >= c->capacity) { out[i] = 0.0; continue; }
    Stripe& s = c->stripes[stripe_of(*c, slot)];
    out[i] = s.tree[s.leaf_base + leaf_of(*c, slot)];
  }
}

// Restore-side sparse apply: exact (alive, mass) values captured at
// snapshot time (no liveness re-derivation — bit-for-bit restores).
void rc_apply_sparse(void* h, int64_t n, const int64_t* idx,
                     const uint8_t* alive, const double* mass) {
  Core* c = static_cast<Core*>(h);
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = idx[i];
    if (slot < 0 || slot >= c->capacity) continue;
    c->alive[slot] = alive[i];
    Stripe& s = c->stripes[stripe_of(*c, slot)];
    tree_set_one(s, leaf_of(*c, slot), mass[i]);
  }
}

void rc_set_counters(void* h, int64_t cursor, int64_t count,
                     int64_t fcount, int64_t frame_dead) {
  Core* c = static_cast<Core*>(h);
  c->cursor = cursor % c->capacity;
  c->count = count;
  c->fcount = fcount;
  c->frame_dead = frame_dead;
}

}  // extern "C"
