"""Central inference: paramless actors, batched action selection (SEED).

Ape-X ships params to every actor and pays the fan-out tax at fleet
width; SEED RL (Espeholt 2020, PAPERS.md) inverts it — the network stays
on the accelerator host, actors become thin env shells that ship
observations and receive actions.  This module is the worker half of
that inversion for this repo's fleets:

  * **CentralInferenceClient** — one persistent CRC-framed connection to
    the serving tier (a ``ServingNetServer`` directly, or the
    ``ServingRouter`` front door for N replicas).  Each fleet step's
    observation batch splits into ``inflight`` contiguous row groups,
    ALL in flight at once as ``F_IREQ`` frames, so the central
    micro-batcher sees real concurrency even from a single worker.  The
    obs payload rides ``encode_xpb_payload`` (in-request frame dedup +
    the hello-negotiated codec) — PR 10's wire economy on the
    obs→inference path.  Transport discipline is runtime/net.py's,
    verbatim: the v2 serve hello carries run-token/wid/attempt, torn or
    bitflipped or oversize reply frames are counted and NEVER decoded
    (the parser faults, the connection retires), recovery is
    reconnect-with-backoff plus whole-request retry, and a request is
    only ever abandoned when the caller's deadline expires — typed
    :class:`InferenceUnavailable`, never a silent wedge.

  * **CentralSelector** — the ``ActorFleet`` action-selection seam.  The
    reply carries greedy actions + q rows + ``param_version``; ε-greedy
    is applied HERE, worker-side, on the returned argmax, from the same
    global ε-ladder slice the worker would use locally (the partition is
    pinned by test — actor identity is placement-independent in both
    inference modes).  The q rows feed the fleet's priority math exactly
    as local q values do.  On a sustained serving outage the selector
    either blocks with a bounded stall counter (default — paramless
    actors stay paramless) or, with ``actor.inference_fallback=local``,
    serves actions from a caller-supplied local fallback (cached-params
    policy_step) until the central path recovers.

Import-light on purpose (stdlib + numpy + runtime.net + utils.metrics):
worker children import this before jax config is pinned.
"""

from __future__ import annotations

import socket
import time
from typing import Callable, List, Optional, Tuple

import numpy as np

from ape_x_dqn_tpu.obs.lineage import BucketExemplars, TraceSpanLog
from ape_x_dqn_tpu.runtime.net import (
    CODEC_OFF,
    CODEC_ZLIB,
    E_CLOSED,
    E_OVERLOADED,
    F_IREP,
    F_SERR,
    HELLO_FLAG_TRACE,
    Backoff,
    FrameParser,
    decode_error,
    decode_inference_reply,
    encode_inference_request,
    frame_bytes,
    serve_hello_ext_bytes,
    wrap_trace,
)
from ape_x_dqn_tpu.runtime.net import F_IREQ as _F_IREQ
from ape_x_dqn_tpu.utils.metrics import LatencyHistogram

_RECV_CHUNK = 1 << 16
_CODEC_IDS = {"off": CODEC_OFF, "zlib": CODEC_ZLIB}


class InferenceUnavailable(Exception):
    """The serving tier did not answer within the caller's deadline
    (across reconnects and whole-request retries) — the typed
    degradation signal; the worker decides block-and-retry vs local
    fallback, never trains on garbage."""


def split_groups(n: int, k: int) -> List[Tuple[int, int]]:
    """[lo, hi) row groups: ``min(k, n)`` contiguous slices, balanced the
    same way worker_slice carves the actor set."""
    k = max(1, min(int(k), int(n)))
    return [(g * n // k, (g + 1) * n // k) for g in range(k)]


class CentralInferenceClient:
    """Pipelined batched-inference client over one serving connection."""

    def __init__(self, host: str, port: int, *, wid: int = 0,
                 attempt: int = 0, token: int = 0, codec: str = "off",
                 dedup: bool = True, inflight: int = 4,
                 connect_timeout_s: float = 2.0, io_timeout_s: float = 5.0,
                 max_frame: int = 64 << 20, seed: int = 0,
                 trace: bool = False, span_recorder=None):
        if codec not in _CODEC_IDS:
            raise ValueError(f"unknown inference codec: {codec}")
        # Cross-tier tracing: negotiated via the v2 hello's flags byte;
        # with it every F_IREQ leads with an i64 trace id and each
        # verified group reply records a client-side hop span (mirrored
        # into ``span_recorder`` — the worker's flight recorder — so the
        # span survives a SIGKILL via the shm event ring).
        self.trace = bool(trace)
        self.spans = TraceSpanLog(depth=64, recorder=span_recorder)
        self.host = host
        self.port = int(port)
        self.wid = int(wid)
        self.attempt = int(attempt)
        self.token = int(token)
        self._codec_id = _CODEC_IDS[codec]
        self._dedup = bool(dedup)
        self.inflight = max(1, int(inflight))
        self._connect_timeout = float(connect_timeout_s)
        self._io_timeout = float(io_timeout_s)
        self._max_frame = int(max_frame)
        self._sock: Optional[socket.socket] = None
        self._parser = FrameParser(max_frame=max_frame)
        self._backoff = Backoff(base_s=0.05, max_s=1.0,
                                seed=(int(wid) << 8) ^ int(attempt) ^ seed)
        self._req_id = 0
        self._out_seq = 0
        self._ever_connected = False
        # Counters (the worker half of the obs `inference` section).
        self.rtt = LatencyHistogram()
        # Newest trace id per rtt bucket: an rtt p99 spike on the fleet
        # rollup links to an assembled cross-tier timeline.
        self.rtt_exemplars = BucketExemplars(self.rtt)
        self.requests = 0        # group requests sent (incl. resends)
        self.rows = 0            # observation rows shipped
        self.replies = 0         # verified F_IREP replies adopted
        self.retries = 0         # whole-request resend rounds
        self.reconnects = 0
        self.shed_seen = 0       # typed E_OVERLOADED refusals
        self.torn_replies = 0    # reply-stream framing faults (never decoded)
        self.errors = 0          # other typed refusals seen
        self.stall_s = 0.0       # wall time blocked past the first attempt
        self.fallback_steps = 0  # selector-side; lives here so one dict ships
        self.param_version = -1  # newest version seen in a reply
        self.wire_bytes_out = 0
        self.logical_bytes_out = 0
        self.dedup_ref_bytes = 0
        self.compressed_frames = 0

    # -- connection --------------------------------------------------------

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _ensure_connected(self) -> bool:
        if self._sock is not None:
            return True
        if not self._backoff.ready():
            return False
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self._connect_timeout
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(serve_hello_ext_bytes(
                self.wid, self.attempt, self.token, self._codec_id,
                flags=HELLO_FLAG_TRACE if self.trace else 0,
            ))
            sock.settimeout(self._io_timeout)
        except OSError:
            self._backoff.fail()
            return False
        self._sock = sock
        self._parser = FrameParser(max_frame=self._max_frame)
        self._out_seq = 0
        # Backoff resets on a verified REPLY, not here: a router with no
        # healthy replica accepts and closes instantly — resetting on
        # connect would turn that outage into a tight loop.
        self.reconnects += int(self._ever_connected)
        self._ever_connected = True
        return True

    # -- the select path ---------------------------------------------------

    def select(self, obs_batch, *, deadline: Optional[float] = None,
               should_stop: Optional[Callable[[], bool]] = None,
               timeout_s: float = 30.0, trace_id: int = 0):
        """One fleet step's action selection: (int32 actions [N],
        float32 q [N, A], param_version).

        Splits the batch into ``inflight`` pipelined group requests; any
        transport fault retires the connection and the WHOLE select
        retries (fresh req_ids, one counted retry round) until the
        deadline — then typed :class:`InferenceUnavailable`.  The greedy
        rows come back exactly as the server computed them; ε is the
        caller's (CentralSelector)."""
        obs = np.ascontiguousarray(obs_batch, dtype=np.uint8)
        n = obs.shape[0]
        groups = split_groups(n, self.inflight)
        t_start = time.monotonic()
        if deadline is None:
            deadline = t_start + float(timeout_s)
        first_round = True
        while time.monotonic() < deadline:
            if should_stop is not None and should_stop():
                raise InferenceUnavailable("stopped while selecting")
            if not self._ensure_connected():
                # Bounded stall accounting: time spent with no serving
                # connection is the outage the operator sees as stall_ms.
                self.stall_s += 0.005
                time.sleep(0.005)
                continue
            if not first_round:
                self.retries += 1
            first_round = False
            t_round = time.monotonic()
            try:
                got = self._round(obs, groups, deadline, should_stop,
                                  trace_id)
            except (OSError, socket.timeout):
                self._drop()
                self._backoff.fail()
                self.stall_s += time.monotonic() - t_round
                continue
            if got is None:
                # Torn stream / typed refusal: the round's time was
                # stalled work — count it, retry whole.
                self.stall_s += time.monotonic() - t_round
                continue
            actions, q, version = got
            self.param_version = max(self.param_version, version)
            return actions, q, version
        raise InferenceUnavailable(
            f"no inference reply within {deadline - t_start:.1f}s "
            f"(retries={self.retries}, reconnects={self.reconnects})"
        )

    def _round(self, obs, groups, deadline, should_stop, trace_id=0):
        """Send every group, await every reply.  None forces a whole
        retry (after a drop/backoff where the transport faulted)."""
        if not self.trace:
            trace_id = 0
        pending: dict = {}
        t_send: dict = {}
        for lo, hi in groups:
            self._req_id += 1
            rid = self._req_id
            sub = obs[lo:hi]
            payload, st = encode_inference_request(
                rid, sub, codec=self._codec_id, dedup=self._dedup
            )
            if self.trace:
                payload = wrap_trace(trace_id, payload)
            self._out_seq += 1
            buf = frame_bytes(_F_IREQ, self._out_seq, [payload])
            self._sock.sendall(buf)
            pending[rid] = (lo, hi)
            t_send[rid] = time.monotonic()
            self.requests += 1
            self.rows += hi - lo
            self.wire_bytes_out += len(buf)
            self.logical_bytes_out += sub.nbytes
            self.dedup_ref_bytes += st["dedup_bytes"]
            self.compressed_frames += int(st["compressed"])
        n = obs.shape[0]
        actions = np.zeros(n, np.int32)
        q: Optional[np.ndarray] = None
        version = None
        while pending:
            if should_stop is not None and should_stop():
                raise InferenceUnavailable("stopped while selecting")
            got = self._parser.next()
            if got is None:
                if self._parser.error is not None:
                    # Torn reply stream (truncation / crc / seq / length):
                    # counted, never decoded, connection retired.
                    self.torn_replies += 1
                    self._drop()
                    self._backoff.fail()
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise socket.timeout("deadline")
                self._sock.settimeout(min(self._io_timeout, remaining))
                data = self._sock.recv(_RECV_CHUNK)
                if not data:
                    raise OSError("connection closed by peer")
                self._parser.feed(data)
                continue
            kind, payload = got
            if kind == F_IREP:
                try:
                    rid, acts, ver, qg = decode_inference_reply(payload)
                except ValueError:
                    # Well-framed but inconsistent reply: protocol
                    # violation — torn discipline, retire + retry.
                    self.torn_replies += 1
                    self._drop()
                    self._backoff.fail()
                    return None
                span = pending.pop(rid, None)
                if span is None:
                    continue        # stale reply from a retried round
                lo, hi = span
                if acts.shape[0] != hi - lo:
                    self.torn_replies += 1
                    self._drop()
                    self._backoff.fail()
                    return None
                if q is None:
                    q = np.zeros((n, qg.shape[1]), np.float32)
                actions[lo:hi] = acts
                q[lo:hi] = qg
                version = ver if version is None else min(version, ver)
                self.replies += 1
                self._backoff.reset()
                rtt_s = time.monotonic() - t_send[rid]
                self.rtt.record(rtt_s)
                self.rtt_exemplars.record(rtt_s, trace_id)
                self.spans.record(trace_id, "inf.select.client",
                                  t_send[rid], rows=hi - lo, wid=self.wid)
                continue
            if kind == F_SERR:
                rid, code, msg = decode_error(payload)
                if code == E_OVERLOADED:
                    # Typed shed: transport is fine, server is shedding —
                    # back off briefly and retry the select whole (an env
                    # step cannot be dropped, unlike a loadgen request).
                    self.shed_seen += 1
                    time.sleep(0.01)
                    return None
                if code == E_CLOSED:
                    # Replica draining: reconnect through the router.
                    self._drop()
                    self._backoff.fail()
                    return None
                self.errors += 1
                self._drop()
                self._backoff.fail()
                return None
            # Unknown kind on this plane: protocol violation — torn.
            self.torn_replies += 1
            self._drop()
            self._backoff.fail()
            return None
        return actions, q, int(version if version is not None else -1)

    # -- observability -----------------------------------------------------

    def stats(self, include_hist: bool = False) -> dict:
        out = {
            "requests": self.requests,
            "rows": self.rows,
            "replies": self.replies,
            "retries": self.retries,
            "reconnects": self.reconnects,
            "shed_seen": self.shed_seen,
            "torn_replies": self.torn_replies,
            "errors": self.errors,
            "stall_ms": round(self.stall_s * 1e3, 1),
            "fallback_steps": self.fallback_steps,
            "param_version": self.param_version,
            "wire_bytes_out": self.wire_bytes_out,
            "logical_bytes_out": self.logical_bytes_out,
            "dedup_ref_bytes": self.dedup_ref_bytes,
            "compressed_frames": self.compressed_frames,
            "rtt": self.rtt.summary(),
            "rtt_exemplars": self.rtt_exemplars.snapshot(),
        }
        if include_hist:
            with self.rtt._lock:
                out["rtt_state"] = {
                    "counts": list(self.rtt._counts),
                    "count": self.rtt._count,
                    "sum": self.rtt._sum,
                    "max": self.rtt._max,
                }
        return out

    def close(self) -> None:
        self._drop()


def aggregate_inference_stats(stats_dicts, mode: str = "central") -> dict:
    """Fleet-wide ``inference`` section from per-client snapshots
    (``stats(include_hist=True)`` dicts, one per worker/selector):
    counter sums + merged round-trip percentiles — the one shape both
    the process pool and the thread fleets report (docs/METRICS.md
    "Inference schema")."""
    dicts = list(stats_dicts)
    agg = {k: 0 for k in (
        "requests", "rows", "replies", "retries", "reconnects",
        "shed_seen", "torn_replies", "errors", "fallback_steps",
        "selects", "outages",
    )}
    stall = 0.0
    version = -1
    wire = logical = 0
    hist = LatencyHistogram()
    exemplars: dict = {}
    for st in dicts:
        for k in agg:
            agg[k] += int(st.get(k, 0))
        stall += float(st.get("stall_ms", 0.0))
        v = int(st.get("param_version", -1))
        version = v if version < 0 else min(version, v)
        wire += int(st.get("wire_bytes_out", 0))
        logical += int(st.get("logical_bytes_out", 0))
        rs = st.get("rtt_state")
        if rs:
            merge_rtt_state(hist, rs)
        ex = st.get("rtt_exemplars")
        if isinstance(ex, dict):
            exemplars.update(ex)
    agg.update(
        rtt_exemplars=exemplars,
        mode=mode,
        workers_reporting=len(dicts),
        stall_ms=round(stall, 1),
        param_version=version,
        wire_bytes_out=wire,
        logical_bytes_out=logical,
        wire_over_logical=(round(wire / logical, 4) if logical else None),
        rtt=hist.summary(),
    )
    return agg


def merge_rtt_state(hist: LatencyHistogram, state: dict) -> None:
    """Fold one client's shipped histogram state (``stats(include_hist=
    True)['rtt_state']``) into an aggregate with the default layout —
    how the pool builds fleet-wide round-trip percentiles from per-worker
    control-queue snapshots."""
    counts = state.get("counts")
    if not counts or len(counts) != len(hist._counts):
        return
    with hist._lock:
        hist._counts = [a + int(b) for a, b in zip(hist._counts, counts)]
        hist._count += int(state.get("count", 0))
        hist._sum += float(state.get("sum", 0.0))
        hist._max = max(hist._max, float(state.get("max", 0.0)))


class CentralSelector:
    """The ActorFleet action-selection seam for central mode.

    ``select(obs, step) -> (actions, q, param_version)`` — greedy rows
    from the serving tier, ε-greedy applied here from the worker's
    global-ladder slice with a seeded numpy stream (the jax in-graph
    ε of local mode, relocated; same ε values, independent stream —
    convergence parity is the test, bit-equality is not claimed).
    """

    def __init__(self, client: CentralInferenceClient, epsilons,
                 num_actions: int, *, seed: int = 0,
                 timeout_s: float = 30.0,
                 trace_sample_rate: float = 0.0,
                 fallback: Optional[Callable] = None,
                 should_stop: Optional[Callable[[], bool]] = None):
        self.client = client
        self.epsilons = np.asarray(epsilons, np.float64).reshape(-1)
        self.num_actions = int(num_actions)
        self._rng = np.random.default_rng(seed)
        self._timeout_s = float(timeout_s)
        # Cross-tier trace sampling (obs.trace_sample_rate's inference
        # twin): a sampled select stamps one 63-bit id shared by all its
        # pipelined groups — the worker → replica timeline's key.
        self._trace_rate = float(trace_sample_rate)
        import random as _random

        self._trace_rng = _random.Random((seed << 8) ^ 0x7A5)
        # Local-fallback seam (actor.inference_fallback=local): a
        # callable (obs, step) -> (actions, q, version) over CACHED
        # params — it applies its own ε in-graph (it IS the local path),
        # so fallback rows skip the worker-side ε below.
        self._fallback = fallback
        self._should_stop = should_stop
        self.selects = 0
        self.outages = 0          # selects that hit the typed deadline

    def select(self, obs, step: int):
        self.selects += 1
        trace_id = 0
        if self._trace_rate and self.client.trace \
                and self._trace_rng.random() < self._trace_rate:
            trace_id = self._trace_rng.getrandbits(63) or 1
        while True:
            try:
                greedy, q, version = self.client.select(
                    obs, timeout_s=self._timeout_s,
                    should_stop=self._should_stop,
                    trace_id=trace_id,
                )
                break
            except InferenceUnavailable:
                self.outages += 1
                if self._should_stop is not None and self._should_stop():
                    raise
                if self._fallback is not None:
                    self.client.fallback_steps += 1
                    return self._fallback(obs, step)
                # No fallback configured: BLOCK with the stall counted
                # (client.stall_s) and retry — a paramless worker has no
                # other source of actions, and a mid-quantum raise would
                # drop the quantum's already-emitted chunks.  The stop
                # event is the only exit.
                continue
        n = greedy.shape[0]
        if self.epsilons.shape[0] != n:
            raise ValueError(
                f"ε slice of {self.epsilons.shape[0]} actors vs obs batch "
                f"of {n}"
            )
        explore = self._rng.random(n) < self.epsilons
        randoms = self._rng.integers(0, self.num_actions, size=n)
        actions = np.where(explore, randoms, greedy).astype(np.int32)
        return actions, q, version

    def stats(self, include_hist: bool = False) -> dict:
        out = self.client.stats(include_hist=include_hist)
        out["selects"] = self.selects
        out["outages"] = self.outages
        return out

    def close(self) -> None:
        self.client.close()
