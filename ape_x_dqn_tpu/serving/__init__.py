"""Policy serving: batched Q-network inference, hot reload, and a fleet.

The training half of Ape-X broadcasts learner params to actor fleets
(runtime/param_store.py) that amortize one jitted forward over a whole
fleet (actors/pool.py).  This package mounts the *inference* half on the
same seams, now network-native end to end:

  * a dynamic micro-batcher coalesces concurrent client requests into
    fixed-bucket batches for one jitted ``argmax Q(s,.)`` call
    (serving/batcher.py);
  * a reload thread polls any ``ParamSource`` — a live trainer's
    ``ParamStore``, a checkpoint dir, a socket param hub, or an APXC
    delta-chunk tail — swapping params atomically between batches
    (serving/server.py, serving/sources.py);
  * a socket front end speaks the length-prefixed CRC-framed
    request/reply protocol into the same batcher
    (serving/net_server.py);
  * a health-aware router balances client connections over N replica
    subprocesses, with learner params fanned out to the whole fleet as
    page-deltas over the runtime/net transport (serving/router.py).

Public surface:
  * :class:`PolicyServer` — submit/act + hot reload + serving metrics;
  * :class:`MicroBatcher` — the bucket-padding deadline batcher;
  * :class:`ServingNetServer` / :class:`ServingClient` — the socket
    request/reply plane;
  * :class:`ServingRouter` / :class:`ServingFleet` /
    :class:`ReplicaProcess` — N replicas behind one front door;
  * ParamSources: :class:`CheckpointParamSource`,
    :class:`SocketParamSource`, :class:`ParamTailSource`
    (+ :class:`ParamTailWriter`);
  * central inference (SEED-style paramless actors):
    :class:`CentralInferenceClient` / :class:`CentralSelector`
    (+ typed :class:`InferenceUnavailable`) — serving/central.py;
  * typed admission errors: :class:`ServerOverloaded`, :class:`ServerClosed`.
"""

from ape_x_dqn_tpu.serving.batcher import (
    MicroBatcher,
    ServedAction,
    ServerClosed,
    ServerOverloaded,
    ServingError,
    bucket_for,
    bucket_sizes,
)
from ape_x_dqn_tpu.serving.central import (
    CentralInferenceClient,
    CentralSelector,
    InferenceUnavailable,
)
from ape_x_dqn_tpu.serving.net_server import ServingClient, ServingNetServer
from ape_x_dqn_tpu.serving.router import (
    ReplicaProcess,
    ServingFleet,
    ServingRouter,
)
from ape_x_dqn_tpu.serving.server import PolicyServer
from ape_x_dqn_tpu.serving.sources import (
    CheckpointParamSource,
    ParamTailSource,
    ParamTailWriter,
    SocketParamSource,
    parse_hub_spec,
)

__all__ = [
    "CentralInferenceClient",
    "CentralSelector",
    "CheckpointParamSource",
    "InferenceUnavailable",
    "MicroBatcher",
    "ParamTailSource",
    "ParamTailWriter",
    "PolicyServer",
    "ReplicaProcess",
    "ServedAction",
    "ServerClosed",
    "ServerOverloaded",
    "ServingClient",
    "ServingError",
    "ServingFleet",
    "ServingNetServer",
    "ServingRouter",
    "SocketParamSource",
    "bucket_for",
    "bucket_sizes",
    "parse_hub_spec",
]
