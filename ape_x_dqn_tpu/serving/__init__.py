"""Policy serving: batched Q-network inference with hot param reload.

The training half of Ape-X broadcasts learner params to actor fleets
(runtime/param_store.py) that amortize one jitted forward over a whole
fleet (actors/pool.py).  This package mounts the *inference* half on the
same two seams: a dynamic micro-batcher coalesces concurrent client
requests into fixed-bucket batches for one jitted ``argmax Q(s,.)`` call,
and a reload thread polls any ``ParamSource`` — a live trainer's
``ParamStore`` or a checkpoint dir — swapping params atomically between
batches, so a training run and a serving tier share one process with zero
dropped requests on update.

Public surface:
  * :class:`PolicyServer` — submit/act + hot reload + serving metrics;
  * :class:`MicroBatcher` — the bucket-padding deadline batcher;
  * :class:`CheckpointParamSource` — ParamSource over a checkpoint dir;
  * typed admission errors: :class:`ServerOverloaded`, :class:`ServerClosed`.
"""

from ape_x_dqn_tpu.serving.batcher import (
    MicroBatcher,
    ServedAction,
    ServerClosed,
    ServerOverloaded,
    ServingError,
    bucket_for,
    bucket_sizes,
)
from ape_x_dqn_tpu.serving.server import PolicyServer
from ape_x_dqn_tpu.serving.sources import CheckpointParamSource

__all__ = [
    "CheckpointParamSource",
    "MicroBatcher",
    "PolicyServer",
    "ServedAction",
    "ServerClosed",
    "ServerOverloaded",
    "ServingError",
    "bucket_for",
    "bucket_sizes",
]
