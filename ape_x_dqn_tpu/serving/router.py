"""Replica fleet + health-aware connection router for the serving tier.

One replica is a ceiling; this module is the horizontal story.  Three
pieces, composable so tests can drive each alone:

  * :class:`ServingRouter` — a TCP front door balancing CLIENT
    CONNECTIONS across replica endpoints.  Routing is health-aware: a
    prober polls each replica's ``/healthz`` (the obs exporter the
    serving tier already runs — 503 on a wedged batcher or stale
    params) and a failing/dead replica DRAINS from rotation — zero new
    connections — while existing splices ride on; it re-enters on
    recovery.  The router splices bytes, it never parses frames: the
    protocol stays end-to-end between client and replica, so a router
    bug cannot corrupt a stream undetected (the frame crc would catch
    it at the replica).
  * :class:`ReplicaProcess` — one serving replica subprocess
    (``python -m ape_x_dqn_tpu.serve --listen … --param-hub …``),
    its ports parsed from the child's own JSONL announcements.
  * :class:`ServingFleet` — N replicas behind one router plus the
    **delta param hub**: a ``runtime/net.NetTransport`` listener the
    replicas subscribe to (``SocketParamSource`` — the worker-fleet
    param path, reused verbatim), so each ``publish`` fans out as
    delta-vs-held-version or full-on-connect framed messages with
    per-push bytes/latency recorded.  A hot reload reaches every
    replica in delta-sized bytes without any replica touching a
    checkpoint dir; a SIGKILLed replica is respawned (jittered
    backoff), reconnects, and full-syncs on connect.

A SIGKILLed replica's in-flight requests die with it — that is the
in-flight window.  Nothing beyond it is lost: the broken splice closes
the client's connection, the client reconnects (the router now routes
it to a live replica) and retries the request whole
(``ServingClient.act``), so the fleet-level contract is zero dropped
requests, proven by ``tools/serving_net_smoke.py`` (verify gate 9).
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Tuple

from ape_x_dqn_tpu.fleet.registry import (
    FleetAnnouncer,
    member_doc,
    member_id_for,
)
from ape_x_dqn_tpu.runtime.net import Backoff, NetTransport

_SPLICE_CHUNK = 1 << 16


class _Endpoint:
    __slots__ = ("rid", "host", "port", "health_url", "alive_fn",
                 "healthy", "routed_total", "active", "last_error")

    def __init__(self, rid: int, host: str, port: int,
                 health_url: Optional[str], alive_fn: Optional[Callable]):
        self.rid = int(rid)
        self.host = host
        self.port = int(port)
        self.health_url = health_url
        self.alive_fn = alive_fn
        self.healthy = True
        self.routed_total = 0
        self.active = 0
        self.last_error: Optional[str] = None


class ServingRouter:
    """Health-aware TCP connection balancer over replica endpoints.

    Balancing is at CONNECTION granularity (round-robin over healthy
    endpoints): the serving protocol multiplexes requests per
    connection already, and connection-level routing keeps the router
    out of the framing entirely.  ``stats()`` is the ``serving_router``
    JSONL / /varz section (docs/METRICS.md, pinned by
    TestMetricsDocSchema).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0, *,
                 probe_interval_s: float = 1.0,
                 probe_timeout_s: float = 1.0,
                 on_event: Optional[Callable] = None):
        self._probe_interval = float(probe_interval_s)
        self._probe_timeout = float(probe_timeout_s)
        self._on_event = on_event
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(port)))
        self._lsock.listen(256)
        self._lsock.settimeout(0.25)
        self.host = host
        self.port = self._lsock.getsockname()[1]
        self._lock = threading.Lock()
        self._eps: Dict[int, _Endpoint] = {}
        self._rr = 0                      # round-robin cursor
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._started = False
        self.routed_total = 0
        self.route_fails = 0
        self.active = 0
        self.splices_broken = 0
        self.probe_failures = 0

    # -- endpoint registry -------------------------------------------------

    def set_endpoint(self, rid: int, host: str, port: int, *,
                     health_url: Optional[str] = None,
                     alive_fn: Optional[Callable] = None) -> None:
        """Register (or replace — respawn) one replica endpoint; it
        enters rotation healthy and the next probe settles the truth."""
        with self._lock:
            self._eps[int(rid)] = _Endpoint(rid, host, port, health_url,
                                            alive_fn)

    def remove_endpoint(self, rid: int) -> None:
        with self._lock:
            self._eps.pop(int(rid), None)

    def set_healthy(self, rid: int, healthy: bool,
                    reason: str = "") -> None:
        """Flip one endpoint's rotation state (the prober's setter; the
        fleet also calls it directly the instant a replica process
        dies — faster than the next probe tick)."""
        with self._lock:
            ep = self._eps.get(int(rid))
            if ep is None or ep.healthy == bool(healthy):
                return
            ep.healthy = bool(healthy)
            ep.last_error = reason or None
        self._event("replica_recovered" if healthy else "replica_drained",
                    rid=int(rid), reason=reason)

    def _event(self, kind: str, **fields) -> None:
        if self._on_event is not None:
            try:
                self._on_event(kind, **fields)
            except Exception:  # noqa: BLE001 — observer must not kill routing
                pass

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ServingRouter":
        if not self._started:
            self._started = True
            for target, name in ((self._accept_loop, "router-accept"),
                                 (self._probe_loop, "router-probe")):
                t = threading.Thread(target=target, name=name, daemon=True)
                t.start()
                self._threads.append(t)
        return self

    def close(self) -> None:
        self._stop.set()
        try:
            self._lsock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(timeout=5.0)

    def __enter__(self) -> "ServingRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- routing -----------------------------------------------------------

    def _pick_order(self) -> List[_Endpoint]:
        """Healthy endpoints in round-robin order (cursor advances per
        pick so consecutive connections spread)."""
        with self._lock:
            eps = [e for e in self._eps.values() if e.healthy]
            if not eps:
                return []
            eps.sort(key=lambda e: e.rid)
            self._rr = (self._rr + 1) % len(eps)
            return eps[self._rr:] + eps[:self._rr]

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _addr = self._lsock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._route_conn, args=(client,),
                                 name="router-splice", daemon=True)
            t.start()

    def _route_conn(self, client: socket.socket) -> None:
        try:
            client.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        upstream = None
        ep = None
        for cand in self._pick_order():
            try:
                upstream = socket.create_connection(
                    (cand.host, cand.port), timeout=2.0
                )
                upstream.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                ep = cand
                break
            except OSError as e:
                # Connect refused/reset: the replica is gone RIGHT NOW —
                # drain it without waiting for the prober's next tick.
                self.set_healthy(cand.rid, False, f"connect: {e}")
        if upstream is None:
            self.route_fails += 1
            try:
                client.close()
            except OSError:
                pass
            return
        with self._lock:
            self.routed_total += 1
            self.active += 1
            ep.routed_total += 1
            ep.active += 1
        done = threading.Event()
        t = threading.Thread(
            target=self._splice, args=(upstream, client, done),
            name="router-splice-up", daemon=True,
        )
        t.start()
        self._splice(client, upstream, done)
        t.join(timeout=5.0)
        with self._lock:
            self.active -= 1
            ep.active -= 1
        for s in (client, upstream):
            try:
                s.close()
            except OSError:
                pass

    def _splice(self, src: socket.socket, dst: socket.socket,
                done: threading.Event) -> None:
        """One direction of a byte splice.  On EOF/error both sockets
        shut down, so the twin direction unblocks — a dead replica
        surfaces to the client as a closed connection within one recv."""
        broken = False
        try:
            while not self._stop.is_set():
                data = src.recv(_SPLICE_CHUNK)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            broken = True
        if broken and not done.is_set():
            self.splices_broken += 1
        done.set()
        for s in (src, dst):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass

    # -- health probing ----------------------------------------------------

    def probe_once(self) -> None:
        """One probe sweep (the prober thread's body; tests drive it
        directly for determinism)."""
        with self._lock:
            eps = list(self._eps.values())
        for ep in eps:
            healthy = True
            reason = ""
            if ep.alive_fn is not None:
                try:
                    healthy = bool(ep.alive_fn())
                    reason = "process dead" if not healthy else ""
                except Exception as e:  # noqa: BLE001
                    healthy, reason = False, f"alive_fn: {e}"
            if healthy and ep.health_url:
                try:
                    with urllib.request.urlopen(
                        ep.health_url, timeout=self._probe_timeout
                    ) as resp:
                        healthy = resp.status == 200
                        reason = f"healthz {resp.status}" if not healthy \
                            else ""
                except Exception as e:  # noqa: BLE001 — conn refused, 503…
                    code = getattr(e, "code", None)
                    healthy = False
                    reason = f"healthz {code}" if code else f"probe: {e}"
            if not healthy:
                self.probe_failures += 1
            self.set_healthy(ep.rid, healthy, reason)

    def _probe_loop(self) -> None:
        while not self._stop.wait(self._probe_interval):
            self.probe_once()

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """The ``serving_router`` section (docs/METRICS.md "Serving
        router schema" — key set pinned by tests/test_obs.py)."""
        with self._lock:
            eps = list(self._eps.values())
            return {
                "port": self.port,
                "replicas": len(eps),
                "healthy": sum(1 for e in eps if e.healthy),
                "active": self.active,
                "routed_total": self.routed_total,
                "route_fails": self.route_fails,
                "splices_broken": self.splices_broken,
                "probe_failures": self.probe_failures,
                "endpoints": {
                    str(e.rid): {
                        "port": e.port,
                        "healthy": e.healthy,
                        "active": e.active,
                        "routed_total": e.routed_total,
                        "last_error": e.last_error,
                    }
                    for e in eps
                },
            }


class ReplicaProcess:
    """One serving replica subprocess and its announced ports.

    The child is ``python -m ape_x_dqn_tpu.serve --listen HOST:0
    --param-hub SPEC --obs-port 0 --duration 0`` (0 = serve until
    signaled); it announces its bound ports as JSONL events on stdout
    (``serving_listen``, ``obs_exporter``) which a reader thread parses
    — no port races, no fixed-port collisions across replicas.
    """

    def __init__(self, rid: int, *, hub_host: str, hub_port: int,
                 hub_token: int, listen_host: str = "127.0.0.1",
                 extra_args: Optional[List[str]] = None,
                 env: Optional[dict] = None):
        self.rid = int(rid)
        self.attempt = 0
        self._hub = (hub_host, int(hub_port), int(hub_token))
        self._listen_host = listen_host
        self._extra = list(extra_args or [])
        self._env = env
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.obs_port: Optional[int] = None
        self.respawns = 0
        self._events: List[dict] = []
        self._reader: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def hub_spec(self) -> str:
        host, port, token = self._hub
        return f"{host}:{port}:{token}:{self.rid}:{self.attempt}"

    def spawn(self) -> "ReplicaProcess":
        assert self.proc is None or self.proc.poll() is not None
        if self.proc is not None:
            self.respawns += 1
            self.attempt += 1
        self.port = self.obs_port = None
        with self._lock:
            self._events = []
        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        ))
        env = dict(self._env if self._env is not None else os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        cmd = [
            sys.executable, "-m", "ape_x_dqn_tpu.serve",
            "--param-hub", self.hub_spec(),
            "--listen", f"{self._listen_host}:0",
            "--obs-port", "0",
            "--duration", "0",
            *self._extra,
        ]
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, env=env, cwd=repo,
        )
        self._reader = threading.Thread(
            target=self._read_stdout, name=f"replica{self.rid}-stdout",
            daemon=True,
        )
        self._reader.start()
        return self

    def _read_stdout(self) -> None:
        proc = self.proc
        try:
            for line in proc.stdout:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                with self._lock:
                    self._events.append(rec)
                    if len(self._events) > 256:
                        del self._events[:-128]
                if rec.get("event") == "serving_listen":
                    self.port = int(rec["port"])
                elif rec.get("event") == "obs_exporter":
                    self.obs_port = int(rec["port"])
        except (ValueError, OSError):
            pass

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def wait_ready(self, timeout: float = 180.0) -> "ReplicaProcess":
        """Block until the child announced both ports (or died)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.port is not None and self.obs_port is not None:
                return self
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {self.rid} exited rc={self.proc.returncode} "
                    "before announcing its ports"
                )
            time.sleep(0.05)
        raise TimeoutError(f"replica {self.rid} not ready in {timeout:.0f}s")

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None

    def kill(self) -> None:
        if self.alive():
            os.kill(self.proc.pid, signal.SIGKILL)

    def terminate(self, timeout: float = 10.0) -> None:
        if self.proc is None:
            return
        if self.proc.poll() is None:
            try:
                self.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5.0)
        if self.proc.stdout is not None:
            self.proc.stdout.close()

    def health_url(self) -> Optional[str]:
        if self.obs_port is None:
            return None
        return f"http://{self._listen_host}:{self.obs_port}/healthz"

    def varz(self, timeout: float = 2.0) -> Optional[dict]:
        """Scrape the replica's /varz (serving + serving_net sections) —
        how the fleet reads per-replica served counts and param_version."""
        if self.obs_port is None:
            return None
        url = f"http://{self._listen_host}:{self.obs_port}/varz"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return json.loads(resp.read())
        except Exception:  # noqa: BLE001 — a dead replica scrapes as None
            return None


class ServingFleet:
    """N replica subprocesses + router + delta param hub, supervised.

    The hub is a ``runtime/net.NetTransport``: each replica holds one
    subscription connection (``--param-hub host:port:token:rid:attempt``),
    ``publish()`` serializes once and fans out page-deltas against the
    version each replica holds (full on first connect / after
    reconnect), with per-push bytes and fan-out latency recorded —
    ``NetTransport.set_params``, the exact machinery the actor fleet
    uses, pointed at serving replicas.

    A dead replica is drained from the router the moment the supervisor
    sees it (``poll()``), respawned on a jittered backoff, re-registered
    on its fresh ports, and full-synced by the hub on connect.
    """

    def __init__(self, *, replicas: int = 2, listen_host: str = "127.0.0.1",
                 listen_port: int = 0, probe_interval_s: float = 0.5,
                 replica_args: Optional[List[str]] = None,
                 respawn: bool = True, on_event: Optional[Callable] = None,
                 env: Optional[dict] = None,
                 registry_addr: Optional[Tuple[str, int]] = None,
                 registry_token: int = 0, heartbeat_s: float = 1.0):
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        self._on_event = on_event
        self._listen_host = listen_host
        self._replica_args = list(replica_args or [])
        self._env = env
        self.hub = NetTransport(host="127.0.0.1", port=0)
        self.router = ServingRouter(
            host=listen_host, port=listen_port,
            probe_interval_s=probe_interval_s, on_event=on_event,
        )
        self.replicas: Dict[int, ReplicaProcess] = {
            rid: ReplicaProcess(
                rid, hub_host="127.0.0.1", hub_port=self.hub.port,
                hub_token=self.hub.token, listen_host=listen_host,
                extra_args=replica_args, env=env,
            )
            for rid in range(int(replicas))
        }
        self._respawn = bool(respawn)
        self._backoffs = {rid: Backoff(base_s=0.5, max_s=10.0, seed=rid)
                          for rid in self.replicas}
        self._version = 0
        self._stop = threading.Event()
        self._super: Optional[threading.Thread] = None
        self.respawns = 0
        # Elastic state (spawn/retire — the autopilot's serving
        # actuators).  _lock guards replicas-dict mutation against the
        # supervisor thread; _spawning holds booting replicas the
        # supervisor registers once their ports announce; a retired rid
        # drains from rotation first, then SIGTERMs after its grace.
        self._lock = threading.Lock()
        self._spawning: Dict[int, ReplicaProcess] = {}
        self.retired: set = set()
        self._retiring: Dict[int, tuple] = {}   # rid -> (t0, grace_s)
        self.spawned = 0
        self.retires = 0
        # Fleet discovery plane (optional): when a registry address is
        # given, every replica that reaches rotation is ANNOUNCED as a
        # serving_replica member (varz_url carried in the doc), so the
        # aggregator adopts it from membership — no driver hand-carries
        # obs ports, and an autopilot-spawned replica is discovered the
        # same way the seed ones are.
        self._announcer: Optional[FleetAnnouncer] = None
        if registry_addr is not None:
            self._announcer = FleetAnnouncer(
                registry_addr[0], int(registry_addr[1]),
                token=int(registry_token),
                member_id=member_id_for(f"serving-fleet-{os.getpid()}"),
                heartbeat_s=float(heartbeat_s), on_event=on_event,
            )

    @property
    def port(self) -> int:
        return self.router.port

    @property
    def param_version(self) -> int:
        return self._version

    def _event(self, kind: str, **fields) -> None:
        if self._on_event is not None:
            try:
                self._on_event(kind, **fields)
            except Exception:  # noqa: BLE001 — observer callback must not kill routing
                pass

    # -- param distribution ------------------------------------------------

    def publish_payload(self, payload: bytes) -> dict:
        """Fan one serialized snapshot out to every connected replica
        (delta where it holds the previous version, full otherwise);
        returns the per-push cost record."""
        self._version += 1
        return self.hub.set_params(payload, self._version)

    def publish(self, params) -> dict:
        import jax

        from ape_x_dqn_tpu.utils.serialization import tree_to_bytes

        return self.publish_payload(tree_to_bytes(jax.device_get(params)))

    # -- lifecycle ---------------------------------------------------------

    def start(self, timeout: float = 240.0) -> "ServingFleet":
        """Spawn every replica (in parallel — jax import + warmup
        dominate), wait for their ports, register them, start routing.

        The supervisor thread starts the moment the children are
        spawned: it pumps the hub's accept loop, and a booting replica
        BLOCKS on its first param sync — the hub must be answering
        hellos while we wait for ports, not after."""
        for rid, rep in self.replicas.items():
            self.hub.make_channel(rid, rep.attempt)
            rep.spawn()
        self._super = threading.Thread(target=self._supervise,
                                       name="fleet-supervisor", daemon=True)
        self._super.start()
        deadline = time.monotonic() + timeout
        for rep in self.replicas.values():
            rep.wait_ready(timeout=max(1.0, deadline - time.monotonic()))
            self._register(rep)
        self.router.start()
        if self._announcer is not None:
            self._announcer.start()
        return self

    def _register(self, rep: ReplicaProcess) -> None:
        self.router.set_endpoint(
            rep.rid, "127.0.0.1", rep.port,
            health_url=rep.health_url(), alive_fn=rep.alive,
        )
        self._announce_replica(rep)

    def _announce_replica(self, rep: ReplicaProcess) -> None:
        if self._announcer is None or rep.port is None:
            return
        varz = "" if rep.obs_port is None else \
            f"http://{self._listen_host}:{rep.obs_port}/varz"
        self._announcer.set_member(member_doc(
            f"serving/replica{rep.rid}", "serving_replica",
            host=self._listen_host, port=int(rep.port),
            incarnation=rep.attempt + 1, varz_url=varz,
        ))
        self._announcer.poke()

    def _supervise(self) -> None:
        """Pump the hub's accept loop, respawn dead replicas (drain-now
        on death, re-enter on recovery), register autopilot-spawned
        replicas once their ports announce, and walk retiring replicas
        through drain → SIGTERM → reap."""
        while not self._stop.wait(0.05):
            self.hub.pump()
            now = time.monotonic()
            with self._lock:
                items = list(self.replicas.items())
            for rid, rep in items:
                if rid in self.retired:
                    # Retirement ladder: the endpoint already left the
                    # router (zero NEW routes); after the grace that lets
                    # in-flight requests finish, SIGTERM the child
                    # (serve.py's drain handler closes its sockets), then
                    # reap and retire the hub channel.
                    t0, grace, signaled = self._retiring.get(
                        rid, (now, 0.0, True)
                    )
                    if rep.alive():
                        if not signaled and now - t0 >= grace:
                            try:
                                rep.proc.send_signal(signal.SIGTERM)
                            except OSError:
                                pass
                            self._retiring[rid] = (t0, grace, True)
                    elif rid in self._retiring:
                        del self._retiring[rid]
                        ch = self.hub._channels.get(rid)
                        if ch is not None:
                            self.hub.drop_channel(rid, ch)
                        self._event("replica_retired_done", rid=rid)
                    continue
                if rep.alive():
                    if rid in self._spawning and rep.port is not None \
                            and rep.obs_port is not None:
                        # Boot (spawn or respawn) came up: fresh ports,
                        # into rotation.
                        self._register(rep)
                        del self._spawning[rid]
                        self._backoffs[rid].reset()
                        self._event(
                            "replica_respawned" if rep.respawns
                            else "replica_ready",
                            rid=rid, port=rep.port, attempt=rep.attempt,
                        )
                    continue
                self.router.set_healthy(rid, False, "process dead")
                self._spawning.pop(rid, None)  # died mid-boot: backoff retry
                if not self._respawn:
                    continue
                b = self._backoffs[rid]
                if not b.ready():
                    continue
                self._event("replica_death", rid=rid,
                            rc=rep.proc.returncode if rep.proc else None)
                b.fail()
                self.respawns += 1
                # Fresh incarnation: new attempt ⇒ new hub channel (the
                # old one's stats fold into the transport's base).  The
                # channel lands before the child can possibly dial in
                # (jax import dominates), and a premature hello would
                # only bounce into the writer's reconnect backoff.
                old = self.hub._channels.get(rid)
                if old is not None:
                    self.hub.drop_channel(rid, old)
                rep.spawn()
                self.hub.make_channel(rid, rep.attempt)
                self._spawning[rid] = rep

    # -- elastic spawn/retire (the autopilot's serving actuators) ----------

    def active_replicas(self) -> List[int]:
        """rids currently contributing capacity (booting counts — its
        slot is claimed); retired rids are out whatever their process
        state."""
        with self._lock:
            return sorted(r for r in self.replicas if r not in self.retired)

    def booting(self) -> List[int]:
        """rids spawned but not yet registered in rotation — the
        autopilot holds further scale-ups while one is in flight."""
        with self._lock:
            return sorted(self._spawning)

    def spawn(self) -> int:
        """Add one replica to a RUNNING fleet (autopilot scale-up):
        fresh rid above every rid ever used, hub channel registered
        before the child can dial in, child spawned NON-blocking — the
        supervisor thread registers it on the router the moment its
        ports announce (``replica_ready``)."""
        with self._lock:
            rid = max(self.replicas) + 1 if self.replicas else 0
            rep = ReplicaProcess(
                rid, hub_host="127.0.0.1", hub_port=self.hub.port,
                hub_token=self.hub.token, listen_host=self._listen_host,
                extra_args=self._replica_args, env=self._env,
            )
            self._backoffs[rid] = Backoff(base_s=0.5, max_s=10.0, seed=rid)
            self.hub.make_channel(rid, rep.attempt)
            rep.spawn()
            self.replicas[rid] = rep
            self._spawning[rid] = rep
            self.spawned += 1
        self._event("replica_spawn", rid=rid)
        return rid

    def retire(self, rid: Optional[int] = None,
               drain_grace_s: float = 2.0) -> Optional[int]:
        """Retire one replica (autopilot scale-down) on the proven
        zero-drop path: the endpoint leaves the router's rotation FIRST
        (``remove_endpoint`` — zero new routes; live splices ride on),
        then after ``drain_grace_s`` the supervisor SIGTERMs the child
        (serve.py's drain handler closes its sockets cleanly) — clients
        cut mid-request reconnect through the router to a live replica
        and retry the request whole.  Default target is the highest
        active rid.  Never SIGKILL."""
        with self._lock:
            candidates = [r for r in self.replicas
                          if r not in self.retired
                          and r not in self._spawning]
            if rid is None:
                rid = max(candidates) if candidates else None
            if rid is None or rid not in candidates:
                return None
            self.retired.add(rid)
            self._retiring[rid] = (time.monotonic(),
                                   float(drain_grace_s), False)
            self._spawning.pop(rid, None)
            self.retires += 1
        self.router.remove_endpoint(rid)
        if self._announcer is not None:
            self._announcer.remove_member(f"serving/replica{rid}")
            self._announcer.poke()
        self._event("replica_retired", rid=rid)
        return rid

    def stop(self) -> None:
        self._stop.set()
        if self._announcer is not None:
            self._announcer.close(leave=True)
        if self._super is not None:
            self._super.join(timeout=5.0)
        for rep in self.replicas.values():
            rep.terminate()
        self.router.close()
        self.hub.close()

    def __enter__(self) -> "ServingFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- observability -----------------------------------------------------

    def replica_varz(self) -> Dict[int, Optional[dict]]:
        with self._lock:
            items = list(self.replicas.items())
        return {rid: rep.varz() for rid, rep in items}

    def stats(self) -> dict:
        hub = self.hub.stats()
        with self._lock:
            replica_items = list(self.replicas.items())
        return {
            "router": self.router.stats(),
            "param": {
                k: hub[k]
                for k in ("connections", "param_pushes", "param_full",
                          "param_delta", "param_bytes", "param_drops",
                          "param_fanout_ms_last", "param_fanout_ms_mean",
                          "param_last_push")
            },
            "respawns": self.respawns,
            "spawned": self.spawned,
            "retires": self.retires,
            "retired": sorted(self.retired),
            "param_version": self._version,
            "replicas": {
                str(rid): {
                    "pid": rep.pid,
                    "alive": rep.alive(),
                    "port": rep.port,
                    "obs_port": rep.obs_port,
                    "attempt": rep.attempt,
                    "respawns": rep.respawns,
                    "retired": rid in self.retired,
                }
                for rid, rep in replica_items
            },
        }
