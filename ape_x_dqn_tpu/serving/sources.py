"""ParamSources beyond the live ParamStore: checkpoints, sockets, tails.

The serving tier mounts the same ``get(have_version) -> (params, version)``
protocol the actor fleets poll (actors/pool.py), so "attach to a live
trainer", "watch a checkpoint dir", "subscribe to a param hub over a
socket" and "tail a delta-chunk file chain" are the same server wiring
with a different source plugged in.  Sources here:

  * :class:`CheckpointParamSource` — checkpoint root dir, keyed on
    ``utils/checkpoint.latest_step`` (orbax commits atomically, so a
    half-written checkpoint is never visible as a new version).
  * :class:`SocketParamSource` — a replica's subscription to the fleet's
    param hub (serving/router.ServingFleet): the runtime/net
    ``NetWriter`` + ``NetParamSource`` pair — delta-or-full framed
    messages, crc-verified patches, reconnect-with-backoff — pointed at
    the serving plane.  A hot reload reaches the replica in delta-sized
    bytes without it ever touching a checkpoint dir.
  * :class:`ParamTailSource` (+ :class:`ParamTailWriter`) — the
    checkpoint-attached fallback: the SAME delta-or-full payloads as
    the socket codec, committed as CRC-framed APXC chunk files
    (``utils/checkpoint_inc.write_chunk`` — tmp+fsync+rename, torn
    files typed `ChunkCorrupt`, never decoded).  Replicas on a shared
    filesystem tail delta-sized files instead of re-reading full
    checkpoints; a corrupt rung walks back to the newest intact full,
    mirroring the replay chain's fallback-restore discipline.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional, Tuple

from ape_x_dqn_tpu.utils.checkpoint import latest_step, restore_checkpoint


class CheckpointParamSource:
    """ParamSource over a checkpoint root dir; version == training step.

    ``state_template`` supplies the TrainState structure/dtypes for the
    orbax restore (an initialized state from runtime/components
    ``build_components`` — the same template resume uses).  Only the
    ``params`` leaf leaves this object: the serving tier never holds the
    optimizer state or target net in memory.
    """

    def __init__(self, root: str, state_template):
        self.root = root
        self._template = state_template

    @property
    def version(self) -> int:
        """Newest committed step (-1 when the dir is empty) — lets the
        server report versions_behind against the dir."""
        step = latest_step(self.root)
        return -1 if step is None else int(step)

    def get(self, have_version: int = -1) -> Optional[Tuple[Any, int]]:
        import jax

        step = latest_step(self.root)
        if step is None or step <= have_version:
            return None
        # restore_checkpoint re-resolves the newest committed step itself,
        # so a checkpoint landing between the probe above and the restore
        # just means we come back one version fresher than probed.
        state, restored_step = restore_checkpoint(self.root, self._template)
        return jax.device_get(state.params), int(restored_step)


def parse_hub_spec(spec: str) -> dict:
    """``host:port:token:wid:attempt`` → a runtime/net.NetWriter spec
    (the string a ServingFleet hands each replica on its command line)."""
    parts = spec.rsplit(":", 4)
    if len(parts) != 5:
        raise ValueError(
            f"param hub spec {spec!r} is not host:port:token:wid:attempt"
        )
    host, port, token, wid, attempt = parts
    return {"host": host, "port": int(port), "token": int(token),
            "wid": int(wid), "attempt": int(attempt)}


class SocketParamSource:
    """Replica-side ParamSource over a fleet param-hub connection.

    Wraps the worker fleet's exact machinery (runtime/net.NetWriter's
    param pump + runtime/transport.NetParamSource's template restore):
    full snapshot on connect, page-deltas against the held version after,
    crc-verified patch, connection-drop + reconnect + full resync on any
    fault.  The replica never touches a checkpoint dir.
    """

    def __init__(self, spec, template):
        from ape_x_dqn_tpu.runtime.net import NetWriter
        from ape_x_dqn_tpu.runtime.transport import NetParamSource

        if isinstance(spec, str):
            spec = parse_hub_spec(spec)
        self._writer = NetWriter(spec)
        self._inner = NetParamSource(self._writer, template)

    @property
    def version(self) -> int:
        """Newest version received (-1 before the first full sync) —
        powers the server's ``versions_behind``."""
        return int(self._writer._param_version)

    @property
    def connected(self) -> bool:
        return self._writer._sock is not None

    def get(self, have_version: int = -1):
        return self._inner.get(have_version)

    def close(self) -> None:
        self._writer.close()


_TAIL_RE = re.compile(r"^pp_(\d{10})_(full|delta)\.apxc$")


def _tail_name(version: int, kind: str) -> str:
    return f"pp_{int(version):010d}_{kind}.apxc"


class ParamTailWriter:
    """Publish params as a delta chain of APXC chunk files.

    Every ``base_every`` publishes (or whenever a delta is impossible /
    not worth it) a full snapshot lands; in between, page-deltas against
    the previous version — the runtime/net codec's exact payloads,
    committed through ``utils/checkpoint_inc.write_chunk`` so a torn
    write is typed, never decoded.  Pruning keeps the current full's
    chain plus the previous full's (the replay-chain retention rule):
    a tail reader mid-walk never has its rung deleted out from under it.
    """

    def __init__(self, root: str, *, base_every: int = 16):
        if base_every < 1:
            raise ValueError("base_every must be >= 1")
        self.root = root
        self._base_every = int(base_every)
        os.makedirs(root, exist_ok=True)
        self._prev_payload: Optional[bytes] = None
        self._version = 0
        self._last_full = 0
        self._prev_full = 0
        self.full_writes = 0
        self.delta_writes = 0
        self.bytes_written = 0

    @property
    def version(self) -> int:
        return self._version

    def publish_payload(self, payload: bytes) -> str:
        """Commit one serialized snapshot; returns the path written."""
        from ape_x_dqn_tpu.runtime.net import build_param_delta
        from ape_x_dqn_tpu.utils.checkpoint_inc import write_chunk

        import numpy as np

        self._version += 1
        v = self._version
        delta = None
        if self._prev_payload is not None \
                and (v - self._last_full) < self._base_every:
            delta = build_param_delta(v, v - 1, self._prev_payload, payload)
        if delta is None:
            kind, body, base = "full", payload, -1
            self._prev_full, self._last_full = self._last_full, v
            self.full_writes += 1
        else:
            kind, body, base = "delta", delta, v - 1
            self.delta_writes += 1
        path = os.path.join(self.root, _tail_name(v, kind))
        self.bytes_written += write_chunk(path, {
            "version": np.int64(v),
            "base": np.int64(base),
            "payload": np.frombuffer(body, dtype=np.uint8),
        })
        self._prev_payload = payload
        self._prune()
        return path

    def publish(self, params) -> str:
        import jax

        from ape_x_dqn_tpu.utils.serialization import tree_to_bytes

        return self.publish_payload(tree_to_bytes(jax.device_get(params)))

    def _prune(self) -> None:
        """Drop files older than the previous full's chain."""
        floor = self._prev_full if self._prev_full > 0 else self._last_full
        for name in os.listdir(self.root):
            m = _TAIL_RE.match(name)
            if m and int(m.group(1)) < floor:
                try:
                    os.unlink(os.path.join(self.root, name))
                except OSError:
                    pass


class ParamTailSource:
    """ParamSource tailing a :class:`ParamTailWriter` chain.

    ``get`` walks to the newest reachable version: the held payload plus
    any consecutive deltas, else the newest intact full plus its deltas.
    Any rung failing CRC/decode (typed ``ChunkCorrupt`` from read_chunk,
    or a delta whose base/crc mismatches) stops that chain and the walk
    falls back to an older full — corrupt bytes never restore, the
    fallback is silent-but-counted (``corrupt_skips``).
    """

    def __init__(self, root: str, template):
        self.root = root
        self._template = template
        self._payload: Optional[bytes] = None
        self._version = -1
        self.corrupt_skips = 0

    def _scan(self):
        """Sorted [(version, kind, path)] of intact-named chain files."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        out = []
        for name in names:
            m = _TAIL_RE.match(name)
            if m:
                out.append((int(m.group(1)), m.group(2),
                            os.path.join(self.root, name)))
        out.sort()
        return out

    @property
    def version(self) -> int:
        entries = self._scan()
        return entries[-1][0] if entries else -1

    def _read(self, path: str) -> Tuple[int, int, bytes]:
        from ape_x_dqn_tpu.utils.checkpoint_inc import read_chunk

        arrays = read_chunk(path)
        return (int(arrays["version"]), int(arrays["base"]),
                arrays["payload"].tobytes())

    def _apply_deltas(self, payload: bytes, version: int,
                      entries) -> Tuple[bytes, int]:
        """Consecutive delta rungs from ``version``+1 upward; stops at a
        gap, a full, or a corrupt/mismatched rung."""
        from ape_x_dqn_tpu.runtime.net import apply_param_delta
        from ape_x_dqn_tpu.utils.checkpoint_inc import ChunkCorrupt

        by_version = {v: (kind, path) for v, kind, path in entries}
        while True:
            nxt = by_version.get(version + 1)
            if nxt is None or nxt[0] != "delta":
                return payload, version
            try:
                v, base, body = self._read(nxt[1])
                if base != version:
                    raise ValueError(
                        f"delta base {base} != held version {version}"
                    )
                _, _, payload = apply_param_delta(payload, body)
            except (ChunkCorrupt, ValueError):
                self.corrupt_skips += 1
                return payload, version
            version = v

    def get(self, have_version: int = -1):
        from ape_x_dqn_tpu.utils.checkpoint_inc import ChunkCorrupt
        from ape_x_dqn_tpu.utils.serialization import restore_like

        entries = self._scan()
        if not entries:
            return None
        # Fast path: extend the held payload by consecutive deltas.
        if self._payload is not None:
            payload, version = self._apply_deltas(
                self._payload, self._version, entries
            )
            if version > self._version:
                self._payload, self._version = payload, version
        best = (self._payload, self._version)
        if best[1] < entries[-1][0]:
            # A full newer than what deltas reach (or no held payload):
            # walk fulls newest-first until one chain restores.
            fulls = [e for e in entries if e[1] == "full"]
            for v, _kind, path in reversed(fulls):
                if v <= best[1]:
                    break
                try:
                    _, _, payload = self._read(path)
                except ChunkCorrupt:
                    self.corrupt_skips += 1
                    continue
                payload, version = self._apply_deltas(payload, v, entries)
                if version > best[1]:
                    best = (payload, version)
                    self._payload, self._version = payload, version
                break
        if best[0] is None or best[1] <= int(have_version):
            return None
        return restore_like(self._template, best[0]), best[1]
