"""ParamSources beyond the live ParamStore: serve from checkpoints on disk.

The serving tier mounts the same ``get(have_version) -> (params, version)``
protocol the actor fleets poll (actors/pool.py), so "attach to a live
trainer" and "watch a checkpoint dir" are the same server wiring with a
different source plugged in.  Here: the checkpoint-dir source, keyed on
``utils/checkpoint.latest_step`` — orbax commits atomically (tmp dir +
rename), so a half-written checkpoint is never visible as a new version.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

from ape_x_dqn_tpu.utils.checkpoint import latest_step, restore_checkpoint


class CheckpointParamSource:
    """ParamSource over a checkpoint root dir; version == training step.

    ``state_template`` supplies the TrainState structure/dtypes for the
    orbax restore (an initialized state from runtime/components
    ``build_components`` — the same template resume uses).  Only the
    ``params`` leaf leaves this object: the serving tier never holds the
    optimizer state or target net in memory.
    """

    def __init__(self, root: str, state_template):
        self.root = root
        self._template = state_template

    @property
    def version(self) -> int:
        """Newest committed step (-1 when the dir is empty) — lets the
        server report versions_behind against the dir."""
        step = latest_step(self.root)
        return -1 if step is None else int(step)

    def get(self, have_version: int = -1) -> Optional[Tuple[Any, int]]:
        import jax

        step = latest_step(self.root)
        if step is None or step <= have_version:
            return None
        # restore_checkpoint re-resolves the newest committed step itself,
        # so a checkpoint landing between the probe above and the restore
        # just means we come back one version fresher than probed.
        state, restored_step = restore_checkpoint(self.root, self._template)
        return jax.device_get(state.params), int(restored_step)
