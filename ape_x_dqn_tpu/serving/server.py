"""PolicyServer: batcher + jitted greedy apply + hot param reload + metrics.

Composition (one arrow per thread boundary):

    clients --submit--> MicroBatcher --bucket batch--> greedy_apply(params)
                                          ^
    ParamSource (ParamStore | checkpoint dir) <--poll-- reload thread

The reload thread polls ``source.get(have_version)`` — the SAME ParamSource
protocol actor fleets use (actors/pool.py sync_params) — and swaps the
``(device_params, version, swap_time)`` triple in one reference assignment.
The batch worker reads that triple exactly once per batch, so every reply
in a batch carries the version that actually produced it and a swap can
never land mid-batch: hot reload with zero dropped requests is structural,
not scheduled.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Optional

import numpy as np

from ape_x_dqn_tpu.models.dueling import build_greedy_apply
from ape_x_dqn_tpu.serving.batcher import (
    MicroBatcher,
    ServedAction,
    ServerOverloaded,
)


class PolicyServer:
    """Multi-client greedy-action service over one Q-network.

    Args:
      network: the flax Q-network (models/dueling.py).
      params: initial host/device params; None pulls the first snapshot
        from ``param_source`` (blocking up to ``source_timeout_s``).
      param_source: optional ``get(have_version) -> (params, version) | None``
        provider (runtime ParamStore, serving CheckpointParamSource, or a
        test stub); polled every ``reload_poll_s`` while running.
      max_batch / max_wait_ms / queue_capacity: batcher knobs (see
        serving/batcher.py for the bucket/deadline/load-shed disciplines).
    """

    def __init__(
        self,
        network,
        params: Optional[Any] = None,
        *,
        param_source: Optional[Any] = None,
        max_batch: int = 32,
        max_wait_ms: float = 5.0,
        queue_capacity: int = 256,
        reload_poll_s: float = 0.25,
        source_timeout_s: float = 30.0,
        apply_delay_ms: float = 0.0,
        delay_seed: int = 0,
    ):
        import jax

        self._jax = jax
        self.network = network
        self._apply = build_greedy_apply(network)
        # Chaos injector (chaos.serving_delay_ms): seeded per-batch sleep
        # in the apply path — makes service time SLEEP-bound so replica
        # capacity genuinely scales on a 1-core host (the serving twin of
        # the slow-env injector; the autopilot smoke's disturbance).
        self._apply_delay_s = float(apply_delay_ms) / 1e3
        self._delay_rng = None
        if self._apply_delay_s > 0:
            import random as _random

            self._delay_rng = _random.Random(0xD31A ^ int(delay_seed))
        self._source = param_source
        self._reload_poll_s = float(reload_poll_s)
        version = 0
        if params is None:
            if param_source is None:
                raise ValueError("need params or param_source")
            params, version = self._poll_first(param_source, source_timeout_s)
        # The live triple: swapped by ONE reference assignment (_swap), read
        # by ONE local bind per batch (_run_batch) — atomic either side.
        self._live = (jax.device_put(params), int(version), time.monotonic())
        self.reload_count = 0
        # Degraded mode (runtime/supervisor.ServingStalenessPolicy): when
        # the param source goes stale past the operator's bound, new
        # submissions shed with the typed ServerOverloaded — for a policy
        # tier feeding live traffic, a loud refusal beats a silently
        # ancient answer.  A bool store, toggled by the policy's check.
        self.degraded = False
        self._stop = threading.Event()
        self._batcher = MicroBatcher(
            self._run_batch,
            max_batch=max_batch,
            max_wait_s=max_wait_ms / 1e3,
            queue_capacity=queue_capacity,
        )
        self._reload_thread = (
            threading.Thread(
                target=self._reload_loop, name="serve-reload", daemon=True
            )
            if param_source is not None
            else None
        )
        self._started = False

    @staticmethod
    def _poll_first(source, timeout_s: float):
        """First snapshot: ``get_blocking`` when the source has it (the
        ParamStore), else a poll loop over the bare protocol."""
        if hasattr(source, "get_blocking"):
            return source.get_blocking(timeout=timeout_s)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            got = source.get(-1)
            if got is not None:
                return got
            time.sleep(0.02)
        raise TimeoutError("param source published nothing within timeout")

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "PolicyServer":
        if not self._started:
            self._started = True
            self._batcher.start()
            if self._reload_thread is not None:
                self._reload_thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        self._batcher.close()
        if self._reload_thread is not None and self._reload_thread.is_alive():
            self._reload_thread.join(timeout=5.0)

    def __enter__(self) -> "PolicyServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def warmup(self, obs_shape) -> None:
        """Compile every bucket shape before opening the doors — first
        requests pay queueing, not XLA compilation."""
        for b in self._batcher.buckets:
            self._run_batch(np.zeros((b, *obs_shape), np.uint8))

    # -- request path -----------------------------------------------------

    def submit(self, obs):
        """Non-blocking: Future of ServedAction.  Typed errors on overload
        — including the degraded stale-params mode, which sheds here (and
        counts with the batcher's load-shed) rather than serving answers
        from a param source known to be dead."""
        if self.degraded:
            self._batcher.shed_count += 1
            raise ServerOverloaded(
                f"serving degraded: params stale "
                f"{self.param_age_s:.1f}s (source quiet past the "
                "configured bound); retry later"
            )
        return self._batcher.submit(obs)

    def act(self, obs, timeout: Optional[float] = 10.0) -> ServedAction:
        """Blocking convenience: one observation -> one ServedAction."""
        return self._batcher.submit(obs).result(timeout=timeout)

    def _run_batch(self, obs):
        params, version, _ = self._live      # one coherent snapshot per batch
        if self._delay_rng is not None:
            # ±25% seeded jitter so paced load doesn't phase-lock.
            time.sleep(self._apply_delay_s
                       * (0.75 + 0.5 * self._delay_rng.random()))
        actions, q = self._jax.device_get(self._apply(params, obs))
        return actions, q, version

    # -- reload path ------------------------------------------------------

    def poll_reload(self) -> bool:
        """One source poll; True if new params were adopted.  The reload
        thread calls this on its cadence; tests and idle-loop callers can
        drive it directly."""
        got = self._source.get(self._live[1])
        if got is None:
            return False
        params, version = got
        # Upload OUTSIDE the swap: requests keep being served on the old
        # params during the transfer; the swap itself is one assignment.
        device_params = self._jax.device_put(params)
        self._live = (device_params, int(version), time.monotonic())
        self.reload_count += 1
        return True

    def _reload_loop(self) -> None:
        while not self._stop.wait(self._reload_poll_s):
            try:
                self.poll_reload()
            except Exception:  # noqa: BLE001 — a flaky source must not
                # kill serving; stale params are the correct degraded mode.
                pass

    # -- observability ----------------------------------------------------

    @property
    def batcher(self) -> "MicroBatcher":
        """The micro-batcher behind this server — the seam the socket
        front end (serving/net_server.py) and the /healthz heartbeat
        registration both mount."""
        return self._batcher

    def attach_transport(self, stats_fn) -> None:
        """Fold a transport's stats into ``stats()`` under ``net`` —
        one snapshot covers the in-process batcher AND its socket front
        end once a ServingNetServer is mounted."""
        self._transport_stats = stats_fn

    @property
    def param_version(self) -> int:
        return self._live[1]

    @property
    def param_age_s(self) -> float:
        """Seconds since the live params were adopted — the staleness
        signal the supervisor's serving policy compares to its bound."""
        return time.monotonic() - self._live[2]

    def stats(self) -> dict:
        """Serving metrics snapshot (the JSONL emit loop's source)."""
        b = self._batcher
        _, version, swapped_at = self._live
        out = {
            "qps": round(b.served.rate(), 1),
            "served_total": int(b.served.total),
            "shed_total": b.shed_count,
            "error_total": b.error_count,
            "queue_depth": b.queue_depth,
            "param_version": version,
            "param_age_s": round(time.monotonic() - swapped_at, 3),
            "degraded": self.degraded,
            "reloads": self.reload_count,
            "batch_hist": {str(k): v for k, v in sorted(b.batch_hist.items())},
            "latency": b.latency.summary(),
            # Canary sensor: latency split by the param_version each
            # batch served under (newest few versions, see MicroBatcher).
            "by_version": {
                str(v): {"replies": row["replies"],
                         "latency": row["hist"].summary()}
                for v, row in sorted(b.by_version.items())
            },
        }
        # Versions behind the source (publishes missed): staleness as the
        # param store defines it, from the serving side.
        if self._source is not None and hasattr(self._source, "version"):
            out["versions_behind"] = max(
                0, int(self._source.version) - version
            )
        if getattr(self, "_transport_stats", None) is not None:
            out["net"] = self._transport_stats()
        return out

    def emit_metrics(self, logger, **extra) -> dict:
        """Flush a serving record onto a utils.metrics.MetricLogger JSONL
        stream under the ``serve/`` namespace."""
        s = self.stats()
        logger.log("serve/qps", s["qps"])
        logger.log("serve/queue_depth", s["queue_depth"])
        logger.log("serve/param_version", s["param_version"])
        logger.log("serve/param_age_s", s["param_age_s"])
        lat = s["latency"]
        if lat.get("count"):
            logger.log("serve/p50_ms", lat["p50_ms"])
            logger.log("serve/p95_ms", lat["p95_ms"])
            logger.log("serve/p99_ms", lat["p99_ms"])
        return logger.emit(
            **{
                "serve/shed_total": s["shed_total"],
                "serve/served_total": s["served_total"],
                "serve/reloads": s["reloads"],
                "serve/batch_hist": s["batch_hist"],
            },
            **extra,
        )
