"""Dynamic micro-batcher: coalesce concurrent requests into bucket batches.

The serving analogue of the actor fleet's one-forward-per-fleet-step
inversion (actors/pool.py): N concurrent clients' observations ride ONE
jitted forward instead of N.  Three disciplines make that a service rather
than a throughput hack:

  * **Fixed bucket shapes.**  Batches pad up to the next power-of-two
    bucket (1, 2, 4, ..., max_batch), so XLA compiles a handful of programs
    — not one per concurrent-request count.  Padded rows replicate a real
    row and are sliced off before reply; per-row argmax means they cannot
    influence real rows (tests/test_serving.py pins this).
  * **Deadline flush.**  A batch launches when it reaches ``max_batch`` OR
    when the oldest member has waited ``max_wait_s`` — p99 queueing latency
    is bounded even at QPS 1 (a lone request never waits for company that
    is not coming).  Under load the deadline is already past when the
    worker frees up, so batches fill from the backlog without any wait.
  * **Admission control.**  The request queue is bounded; a full queue
    rejects with the typed :class:`ServerOverloaded` instead of queueing
    unboundedly — the bounded-queue discipline runtime/process_actors.py
    established for experience transport, applied to the request path.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Callable, List, NamedTuple, Optional, Sequence

import numpy as np

from ape_x_dqn_tpu.utils.metrics import LatencyHistogram, RateCounter


class ServingError(Exception):
    """Base class for typed serving-path errors."""


class ServerOverloaded(ServingError):
    """Admission control rejected the request (bounded queue full)."""


class ServerClosed(ServingError):
    """The server is shut down; the request was not (or will not be) served."""


class ServedAction(NamedTuple):
    """One client's reply: greedy action + the evidence behind it."""

    action: int
    q_values: np.ndarray     # float32 [A] — this row's Q(s, .)
    param_version: int       # version of the params that produced it
    latency_s: float         # enqueue -> reply, incl. queueing + compute


class _Request(NamedTuple):
    obs: np.ndarray
    future: Future
    t_enqueue: float


_SENTINEL = None


def bucket_sizes(max_batch: int) -> List[int]:
    """Power-of-two ladder up to (and always including) ``max_batch``."""
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1")
    sizes = []
    b = 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    return sizes


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits ``n`` requests."""
    for b in buckets:
        if b >= n:
            return b
    raise ValueError(f"batch of {n} exceeds the largest bucket {buckets[-1]}")


class MicroBatcher:
    """Bounded request queue + worker thread running the batched forward.

    ``run_batch(padded_obs) -> (actions, q_values, param_version)`` is the
    compute seam the server supplies: it snapshots params ONCE per call, so
    a param swap can never land mid-batch (version atomicity is per batch
    by construction).
    """

    def __init__(
        self,
        run_batch: Callable,
        max_batch: int = 32,
        max_wait_s: float = 0.005,
        queue_capacity: int = 256,
        name: str = "serve-batcher",
    ):
        if queue_capacity < 1:
            raise ValueError("queue_capacity must be >= 1")
        self._run_batch = run_batch
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_s)
        self.buckets = bucket_sizes(self.max_batch)
        self._q: queue.Queue = queue.Queue(maxsize=int(queue_capacity))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True
        )
        # Metrics (read by PolicyServer.stats / the JSONL emit loop).
        self.latency = LatencyHistogram()
        self.served = RateCounter()
        self.shed_count = 0
        self.error_count = 0
        self.batch_hist: dict[int, int] = {}   # real batch size -> count
        # Per-param_version latency split (newest few versions): the
        # canary sensor — version atomicity per batch means one lookup
        # covers the whole batch.
        self.by_version: dict[int, dict] = {}
        self.max_versions = 4
        self._started = False
        # Liveness for /healthz (obs.Health age fn): the worker loop
        # stamps this every iteration — including idle ones — so a stale
        # heartbeat means the batcher thread is wedged, not just unloaded.
        self.heartbeat = time.monotonic()

    # -- client side ------------------------------------------------------

    def submit(self, obs: np.ndarray) -> Future:
        """Enqueue one observation; returns a Future of ServedAction.

        Raises :class:`ServerOverloaded` when the bounded queue is full
        (load shed) and :class:`ServerClosed` after shutdown.
        """
        if self._stop.is_set():
            raise ServerClosed("server is shut down")
        req = _Request(np.asarray(obs), Future(), time.monotonic())
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self.shed_count += 1
            raise ServerOverloaded(
                f"request queue at capacity ({self._q.maxsize}); retry later"
            ) from None
        return req.future

    @property
    def queue_depth(self) -> int:
        return self._q.qsize()

    # -- worker side ------------------------------------------------------

    def start(self) -> "MicroBatcher":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def _drain_now(self, batch: List[_Request]) -> None:
        """Take whatever is immediately available, up to max_batch."""
        try:
            while len(batch) < self.max_batch:
                r = self._q.get_nowait()
                if r is _SENTINEL:
                    return
                batch.append(r)
        except queue.Empty:
            pass

    def _gather(self, first: _Request) -> List[_Request]:
        """Fill a batch: until max_batch or the FIRST member's deadline.

        Deadline is anchored at the oldest request's enqueue time, not at
        gather start — a request that already queued behind a slow batch
        gets correspondingly less extra wait, keeping the max-wait bound a
        property of the request, not of worker scheduling luck.
        """
        batch = [first]
        deadline = first.t_enqueue + self.max_wait_s
        while len(batch) < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._drain_now(batch)
                break
            try:
                r = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if r is _SENTINEL:
                break
            batch.append(r)
        return batch

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.heartbeat = time.monotonic()
            try:
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            if first is _SENTINEL:
                continue
            self._serve_one_batch(self._gather(first))

    def _serve_one_batch(self, batch: List[_Request]) -> None:
        n = len(batch)
        if n == 0:
            return
        bucket = bucket_for(n, self.buckets)
        obs = np.stack([r.obs for r in batch])
        if bucket > n:
            # Replicate the first row — in-distribution values, and row-wise
            # argmax keeps padding inert regardless of content.
            pad = np.broadcast_to(obs[:1], (bucket - n, *obs.shape[1:]))
            obs = np.concatenate([obs, pad], axis=0)
        try:
            actions, q_values, version = self._run_batch(obs)
        except Exception as e:  # noqa: BLE001 — delivered to each waiter
            self.error_count += n
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        done = time.monotonic()
        self.batch_hist[n] = self.batch_hist.get(n, 0) + 1
        self.served.add(n)
        vrow = self.by_version.get(int(version))
        if vrow is None:
            vrow = self.by_version[int(version)] = {
                "replies": 0, "hist": LatencyHistogram()
            }
            while len(self.by_version) > self.max_versions:
                del self.by_version[min(self.by_version)]
        vrow["replies"] += n
        for i, r in enumerate(batch):
            latency = done - r.t_enqueue
            self.latency.record(latency)
            vrow["hist"].record(latency)
            r.future.set_result(
                ServedAction(
                    int(actions[i]),
                    np.asarray(q_values[i]),
                    int(version),
                    latency,
                )
            )

    def close(self) -> None:
        """Stop the worker; fail queued-but-unserved requests typed."""
        self._stop.set()
        try:
            self._q.put_nowait(_SENTINEL)
        except queue.Full:
            pass
        if self._started:
            self._thread.join(timeout=5.0)
        try:
            while True:
                r = self._q.get_nowait()
                if r is not _SENTINEL and not r.future.done():
                    r.future.set_exception(ServerClosed("server shut down"))
        except queue.Empty:
            pass
