"""Socket front end for the policy tier: framed request/reply serving.

PolicyServer (serving/server.py) batches beautifully but only speaks
in-process Python — one replica, one host, no fleet.  This module puts a
real transport in front of the SAME micro-batcher: a nonblocking
acceptor/pump thread speaks the length-prefixed binary protocol from
runtime/net.py (``u32 len | u32 crc | i64 seq | u8 kind`` — the exact
framing discipline the experience plane proved under the adversarial
decode matrix) and feeds every verified request straight into
``PolicyServer.submit``.  The reply rides back on the batcher thread's
future callback, so the select loop never blocks on compute and the
batcher never blocks on a slow client (per-connection outboxes, flushed
as sockets drain).

Contracts, mirrored from the experience transport:

  * **Torn frames are counted, never decoded.**  Any framing fault —
    truncation mid-prefix or mid-payload, a crc bitflip, a seq skip, an
    oversize length prefix (bounded by ``serving.max_request_bytes``,
    far below the transport's GiB sanity cap) — retires the CONNECTION;
    nothing from the bad stream reaches the batcher.
  * **Typed refusals, not silent drops.**  Admission-control shed
    (``ServerOverloaded``) and shutdown (``ServerClosed``) go back as
    ``F_SERR`` frames with typed codes, so a closed-loop client can
    distinguish "retry later" from "gone" from "my bug".
  * **Every reply carries ``param_version``** (the batcher snapshots
    params once per batch), so a fleet-wide hot reload is observable
    per-reply from the client side.
  * **Per-request latency** (request decoded → reply enqueued) on the
    existing ``utils.metrics.LatencyHistogram`` — p50/p95/p99 on the
    same instrument the in-process path reports.

``ServingClient`` is the reference client: blocking closed-loop calls
with reconnect-with-backoff (runtime/net.Backoff) and whole-request
retry, so a replica dying mid-flight costs the client a reconnect, not
an answer — the zero-drop arithmetic the router smoke pins.
"""

from __future__ import annotations

import collections
import select
import socket
import threading
import time
from typing import Optional

from ape_x_dqn_tpu.obs.lineage import BucketExemplars, TraceSpanLog
from ape_x_dqn_tpu.runtime.net import (
    CODEC_OFF,
    E_BAD_REQUEST,
    E_CLOSED,
    E_INTERNAL,
    E_OVERLOADED,
    F_IREP,
    F_IREQ,
    F_SERR,
    F_SREP,
    F_SREQ,
    HELLO_FLAG_TRACE,
    SERVE_HELLO,
    SERVE_HELLO_EXT,
    SERVE_MAGIC,
    SERVE_VERSION_EXT,
    Backoff,
    FrameParser,
    decode_error,
    decode_inference_request,
    decode_reply,
    decode_request,
    encode_error,
    encode_inference_reply,
    encode_reply,
    encode_request,
    frame_bytes,
    parse_serve_hello,
    parse_serve_hello_ext,
    serve_hello_bytes,
    serve_hello_ext_bytes,
    split_trace,
    wrap_trace,
)
from ape_x_dqn_tpu.serving.batcher import (
    ServedAction,
    ServerClosed,
    ServerOverloaded,
    ServingError,
)
from ape_x_dqn_tpu.utils.metrics import LatencyHistogram

_RECV_CHUNK = 1 << 16
_HELLO_SIZE = len(serve_hello_bytes())
_MAX_VERSIONS = 4   # per-version latency splits kept (newest versions)


class _NetConn:
    """One client connection's state, owned by the pump thread (outbox
    appends come from batcher callbacks under the server lock)."""

    __slots__ = ("sock", "parser", "hello", "hello_need", "hello_done",
                 "wid", "codec", "flags", "outbox", "out_off", "out_seq",
                 "bytes_in", "bytes_out", "inflight")

    def __init__(self, sock: socket.socket, max_frame: int):
        self.sock = sock
        self.parser = FrameParser(max_frame=max_frame)
        self.hello = bytearray()          # hello bytes gathered so far
        self.hello_need = _HELLO_SIZE     # grows for a v2 hello
        self.hello_done = False
        self.wid: Optional[int] = None    # v2 hellos: the fleet worker id
        self.codec = CODEC_OFF            # negotiated obs-payload codec
        self.flags = 0                    # v2 hello feature flags (trace)
        self.outbox: collections.deque = collections.deque()
        self.out_off = 0                  # send offset into outbox[0]
        self.out_seq = 0
        self.bytes_in = 0
        self.bytes_out = 0
        self.inflight = 0                 # submitted, reply not yet queued


class ServingNetServer:
    """Multi-client socket acceptor over one PolicyServer.

    One daemon thread runs accept + recv + parse + submit + flush in a
    select loop; batcher-thread future callbacks enqueue replies and wake
    it through a socketpair.  ``stats()`` is the ``serving_net`` JSONL /
    /varz section (docs/METRICS.md, pinned by TestMetricsDocSchema).
    """

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0, *,
                 max_request_bytes: int = 8 << 20,
                 run_token: int = 0, name: str = "serving-net"):
        self._server = server
        self._max_frame = int(max_request_bytes)
        # Fleet-internal hello discipline (central inference): a nonzero
        # run_token makes every v2 hello prove it belongs to THIS run —
        # a stale worker from another run (or a guessing client) is
        # rejected before any framing state.  v1 anonymous hellos stay
        # accepted either way: the single-request front door is public.
        self._run_token = int(run_token)
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(port)))
        self._lsock.listen(256)
        self._lsock.setblocking(False)
        self.host = host
        self.port = self._lsock.getsockname()[1]
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._lock = threading.Lock()     # conn registry + outboxes
        self._conns: dict = {}            # fileno -> _NetConn
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._started = False
        # Counters (the serving_net schema).
        self.latency = LatencyHistogram()
        # Trace exemplars: the newest sampled trace id per latency
        # bucket, so a p99 spike on the fleet rollup links to an
        # assembled cross-tier timeline instead of a bare number.
        self.exemplars = BucketExemplars(self.latency)
        # Per-param_version split of the reply latency (the canary
        # sensor): newest _MAX_VERSIONS versions only — a long run
        # reloads thousands of times, the comparison needs two or three.
        self._by_version: dict = {}   # version -> {replies, hist}
        self.accepted = 0
        self.requests = 0
        self.replies = 0
        self.shed = 0
        self.errors = 0          # bad requests + batch exceptions replied
        self.torn_frames = 0
        self.bad_hellos = 0
        self.token_rejects = 0   # v2 hellos with the wrong run token
        self.orphaned = 0        # replies whose connection was already gone
        # Fleet-internal inference traffic (F_IREQ/F_IREP): batched
        # requests and the rows they carried, plus per-source accounting
        # keyed by the hello's worker id (the obs `sources` sub-dict).
        self.inference_requests = 0
        self.inference_rows = 0
        self.inference_replies = 0
        self._sources: dict = {}
        # Cross-tier trace spans: a trace-negotiated connection's requests
        # lead with an i64 trace id; the server records its hop (decode →
        # reply queued) plus the batcher leg, and the fleet aggregator
        # collects them off this process's /varz into e2e timelines.
        self.spans = TraceSpanLog(depth=64)
        # Retired-connection byte history (a reconnecting client must not
        # take its traffic with it — the NetTransport._base discipline).
        self._bytes_in_closed = 0
        self._bytes_out_closed = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ServingNetServer":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        self._wake()
        if self._started:
            self._thread.join(timeout=5.0)
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            try:
                c.sock.close()
            except OSError:
                pass
        try:
            self._lsock.close()
        except OSError:
            pass
        self._wake_r.close()
        self._wake_w.close()

    def __enter__(self) -> "ServingNetServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass

    # -- pump thread -------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                socks = {c.sock: c for c in self._conns.values()}
                wlist = [c.sock for c in self._conns.values() if c.outbox]
            rlist = [self._lsock, self._wake_r, *socks]
            try:
                r, w, _ = select.select(rlist, wlist, [], 0.25)
            except (OSError, ValueError):
                # A socket closed under us mid-select: rebuild the sets.
                time.sleep(0.005)
                continue
            if self._wake_r in r:
                try:
                    while self._wake_r.recv(4096):
                        pass
                except OSError:
                    pass
            if self._lsock in r:
                self._accept_pending()
            for sock in w:
                conn = socks.get(sock)
                if conn is not None:
                    self._flush(conn)
            for sock in r:
                conn = socks.get(sock)
                if conn is not None:
                    self._on_readable(conn)

    def _accept_pending(self) -> None:
        while True:
            try:
                sock, _addr = self._lsock.accept()
            except (BlockingIOError, InterruptedError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            self.accepted += 1
            with self._lock:
                self._conns[sock.fileno()] = _NetConn(sock, self._max_frame)

    def _retire(self, conn: _NetConn, torn: bool = False) -> None:
        """Close one connection; a partial frame left in its parser (or a
        parser fault) counts torn — detected, never delivered."""
        if torn or conn.parser.pending() or conn.parser.error is not None:
            self.torn_frames += 1
        with self._lock:
            self._conns.pop(conn.sock.fileno(), None)
            self._bytes_in_closed += conn.bytes_in
            self._bytes_out_closed += conn.bytes_out
        try:
            conn.sock.close()
        except OSError:
            pass

    def _on_readable(self, conn: _NetConn) -> None:
        while True:
            try:
                data = conn.sock.recv(_RECV_CHUNK)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._retire(conn)
                return
            if not data:
                self._retire(conn)
                return
            conn.bytes_in += len(data)
            while not conn.hello_done and data:
                need = conn.hello_need - len(conn.hello)
                conn.hello += data[:need]
                data = data[need:]
                if len(conn.hello) < conn.hello_need:
                    break
                if not self._admit_hello(conn):
                    return
            if not conn.hello_done:
                continue
            if data:
                conn.parser.feed(data)
        if conn.hello_done:
            self._drain_frames(conn)

    def _admit_hello(self, conn: _NetConn) -> bool:
        """Validate the gathered hello bytes (v1 anonymous or the v2
        fleet extension).  A v2 version word promises the extension
        struct right behind it — grow the want and keep gathering.
        False = rejected and retired (nothing framed yet)."""
        buf = bytes(conn.hello)
        if len(buf) == _HELLO_SIZE:
            if parse_serve_hello(buf):
                conn.hello_done = True
                return True
            try:
                magic, version = SERVE_HELLO.unpack(buf)
            except Exception:  # noqa: BLE001 — malformed header
                magic, version = b"", -1
            if magic == SERVE_MAGIC and version == SERVE_VERSION_EXT:
                conn.hello_need = _HELLO_SIZE + SERVE_HELLO_EXT.size
                return True
            self.bad_hellos += 1
            self._retire(conn)
            return False
        ext = parse_serve_hello_ext(buf[_HELLO_SIZE:])
        if ext is None:
            self.bad_hellos += 1
            self._retire(conn)
            return False
        if self._run_token and ext["token"] != self._run_token:
            self.token_rejects += 1
            self.bad_hellos += 1
            self._retire(conn)
            return False
        conn.wid = ext["wid"]
        conn.codec = ext["codec"]
        conn.flags = ext["flags"]
        conn.hello_done = True
        return True

    def _drain_frames(self, conn: _NetConn) -> None:
        while True:
            got = conn.parser.next()
            if got is None:
                if conn.parser.error is not None:
                    self._retire(conn, torn=True)
                return
            kind, payload = got
            if kind == F_SREQ:
                self._handle_request(conn, payload)
            elif kind == F_IREQ:
                self._handle_inference(conn, payload)
            else:
                # Protocol violation (reply kinds only flow server→client):
                # stream corruption, connection-level recovery.
                self._retire(conn, torn=True)
                return

    def _handle_request(self, conn: _NetConn, payload: bytes) -> None:
        t0 = time.monotonic()
        trace_id = 0
        try:
            if conn.flags & HELLO_FLAG_TRACE:
                trace_id, payload = split_trace(payload)
            req_id, obs = decode_request(bytes(payload))
        except ValueError as e:
            self.errors += 1
            self._enqueue(conn, F_SERR, encode_error(0, E_BAD_REQUEST,
                                                     str(e)))
            return
        self.requests += 1
        try:
            fut = self._server.submit(obs)
        except ServerOverloaded as e:
            self.shed += 1
            self._enqueue(conn, F_SERR,
                          encode_error(req_id, E_OVERLOADED, str(e)))
            return
        except ServerClosed as e:
            self._enqueue(conn, F_SERR, encode_error(req_id, E_CLOSED,
                                                     str(e)))
            return
        conn.inflight += 1
        fut.add_done_callback(
            lambda f, c=conn, rid=req_id, t=t0, tid=trace_id:
            self._complete(c, rid, t, f, tid)
        )

    def _complete(self, conn: _NetConn, req_id: int, t0: float,
                  fut, trace_id: int = 0) -> None:
        """Batcher-thread callback: encode the reply and queue it on the
        connection's outbox (or count it orphaned if the client is gone —
        it has already reconnected and retried elsewhere)."""
        exc = fut.exception()
        if exc is None:
            res: ServedAction = fut.result()
            body = encode_reply(req_id, res.action, res.param_version,
                                res.q_values)
            kind = F_SREP
        elif isinstance(exc, ServerClosed):
            body, kind = encode_error(req_id, E_CLOSED, str(exc)), F_SERR
        else:
            self.errors += 1
            body = encode_error(req_id, E_INTERNAL,
                                f"{type(exc).__name__}: {exc}")
            kind = F_SERR
        conn.inflight -= 1
        if not self._enqueue(conn, kind, body):
            self.orphaned += 1
            return
        if exc is None:
            self.replies += 1
            self._record_reply(res.param_version,
                               time.monotonic() - t0, trace_id)
            self.spans.record(trace_id, "serve.request", t0, wid=conn.wid)

    def _record_reply(self, version: int, dt: float, trace_id: int) -> None:
        """One reply's latency, recorded three ways: the lifetime
        histogram, its bucket exemplar (the trace id that landed there),
        and the per-param_version split the canary comparison reads."""
        self.latency.record(dt)
        self.exemplars.record(dt, trace_id)
        with self._lock:
            row = self._by_version.get(int(version))
            if row is None:
                row = self._by_version[int(version)] = {
                    "replies": 0, "hist": LatencyHistogram()
                }
                while len(self._by_version) > _MAX_VERSIONS:
                    del self._by_version[min(self._by_version)]
            row["replies"] += 1
            row["hist"].record(dt)

    # -- batched fleet inference (F_IREQ/F_IREP) ---------------------------

    def _source_count(self, wid, rows: int = 0, replies: int = 0) -> None:
        if wid is None:
            return
        with self._lock:
            src = self._sources.setdefault(
                str(wid), {"requests": 0, "rows": 0, "replies": 0}
            )
            if rows:
                src["requests"] += 1
                src["rows"] += rows
            if replies:
                src["replies"] += replies

    def _handle_inference(self, conn: _NetConn, payload: bytes) -> None:
        """One batched request: every row rides the micro-batcher as its
        own submit (so rows pad/batch with everything else in flight —
        the whole point of central inference), and the reply goes out
        when the LAST row's future lands.  ε is never applied here: the
        reply carries greedy actions + q rows, the worker's ladder slice
        stays worker-side (pinned by test)."""
        t0 = time.monotonic()
        trace_id = 0
        try:
            if conn.flags & HELLO_FLAG_TRACE:
                trace_id, payload = split_trace(payload)
            req_id, rows = decode_inference_request(
                payload, allow_zlib=conn.codec != CODEC_OFF,
                max_bytes=self._max_frame,
            )
        except ValueError as e:
            # Well-framed but undecodable (the crc already verified the
            # bytes): typed, not torn — the single-request discipline.
            self.errors += 1
            self._enqueue(conn, F_SERR,
                          encode_error(0, E_BAD_REQUEST, str(e)))
            return
        self.inference_requests += 1
        self.inference_rows += len(rows)
        self.requests += 1
        self._source_count(conn.wid, rows=len(rows))
        futures = []
        try:
            for obs in rows:
                futures.append(self._server.submit(obs))
        except ServerOverloaded as e:
            # Whole-request shed: the worker retries the group whole.
            # Rows already admitted complete unobserved (greedy inference
            # is pure — serving them costs one padded row each).
            self.shed += 1
            self._enqueue(conn, F_SERR,
                          encode_error(req_id, E_OVERLOADED, str(e)))
            return
        except ServerClosed as e:
            self._enqueue(conn, F_SERR,
                          encode_error(req_id, E_CLOSED, str(e)))
            return
        conn.inflight += 1
        agg = {"lock": threading.Lock(), "left": len(futures),
               "rows": [None] * len(futures), "exc": None,
               "trace_id": trace_id, "t_submit": time.monotonic()}
        for i, fut in enumerate(futures):
            fut.add_done_callback(
                lambda f, c=conn, rid=req_id, t=t0, a=agg, k=i:
                self._inference_row_done(c, rid, t, a, k, f)
            )

    def _inference_row_done(self, conn: _NetConn, req_id: int, t0: float,
                            agg: dict, k: int, fut) -> None:
        """Batcher-thread callback, once per row; the LAST row assembles
        and queues the F_IREP (or one typed error for the group)."""
        exc = fut.exception()
        with agg["lock"]:
            if exc is not None:
                agg["exc"] = exc
            else:
                agg["rows"][k] = fut.result()
            agg["left"] -= 1
            if agg["left"] > 0:
                return
        import numpy as np

        conn.inflight -= 1
        exc = agg["exc"]
        if exc is not None:
            if isinstance(exc, ServerClosed):
                body, kind = encode_error(req_id, E_CLOSED, str(exc)), F_SERR
            else:
                self.errors += 1
                body = encode_error(req_id, E_INTERNAL,
                                    f"{type(exc).__name__}: {exc}")
                kind = F_SERR
            if not self._enqueue(conn, kind, body):
                self.orphaned += 1
            return
        results = agg["rows"]
        actions = np.asarray([r.action for r in results], np.int32)
        q = np.stack([np.asarray(r.q_values, np.float32) for r in results])
        # Version floor: rows may straddle a hot reload (different
        # batches); the FLEET's freshness claim is the oldest row's.
        version = min(int(r.param_version) for r in results)
        body = encode_inference_reply(req_id, actions, version, q)
        if not self._enqueue(conn, F_IREP, body):
            self.orphaned += 1
            return
        self.replies += 1
        self.inference_replies += 1
        self._source_count(conn.wid, replies=1)
        tid = agg["trace_id"]
        self._record_reply(version, time.monotonic() - t0, tid)
        # Two hops of the e2e inference timeline: the replica's whole
        # service span (decode → reply queued) and the batcher leg inside
        # it (rows submitted → last row's future landed).
        self.spans.record(tid, "serve.infer", t0, wid=conn.wid,
                          rows=len(results))
        self.spans.record(tid, "serve.batch", agg["t_submit"], wid=conn.wid)

    def _enqueue(self, conn: _NetConn, kind: int, body: bytes) -> bool:
        """Queue one outbound frame; False if the connection is gone.
        Seq is assigned under the lock, so outbox order == seq order even
        with the batcher and pump threads both replying."""
        with self._lock:
            if self._conns.get(conn.sock.fileno()) is not conn:
                return False
            conn.out_seq += 1
            conn.outbox.append(frame_bytes(kind, conn.out_seq, [body]))
        self._wake()
        return True

    def _flush(self, conn: _NetConn) -> None:
        while True:
            with self._lock:
                if not conn.outbox:
                    return
                buf = conn.outbox[0]
            try:
                n = conn.sock.send(memoryview(buf)[conn.out_off:])
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._retire(conn)
                return
            conn.bytes_out += n
            conn.out_off += n
            if conn.out_off >= len(buf):
                conn.out_off = 0
                with self._lock:
                    if conn.outbox:
                        conn.outbox.popleft()

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """The ``serving_net`` section (docs/METRICS.md "Serving net
        schema" — key set pinned by tests/test_obs.py)."""
        with self._lock:
            conns = list(self._conns.values())
            sources = {k: dict(v) for k, v in self._sources.items()}
            by_version = {
                str(v): {"replies": row["replies"],
                         "latency": row["hist"].summary(),
                         "latency_buckets": row["hist"].buckets()}
                for v, row in sorted(self._by_version.items())
            }
        return {
            "port": self.port,
            "connections": len(conns),
            "accepted": self.accepted,
            "requests": self.requests,
            "replies": self.replies,
            "shed": self.shed,
            "errors": self.errors,
            "torn_frames": self.torn_frames,
            "bad_hellos": self.bad_hellos,
            "token_rejects": self.token_rejects,
            "orphaned": self.orphaned,
            "inference_requests": self.inference_requests,
            "inference_rows": self.inference_rows,
            "inference_replies": self.inference_replies,
            "sources": sources,
            "inflight": sum(c.inflight for c in conns),
            "bytes_in": sum(c.bytes_in for c in conns)
            + self._bytes_in_closed,
            "bytes_out": sum(c.bytes_out for c in conns)
            + self._bytes_out_closed,
            "param_version": int(getattr(self._server, "param_version", -1)),
            "latency": self.latency.summary(),
            # Fleet-rollup surfaces (obs/fleet.py): raw buckets so the
            # aggregator can merge replicas bucket-wise, and this
            # process's recent cross-tier trace spans.
            "latency_buckets": self.latency.buckets(),
            "latency_exemplars": self.exemplars.snapshot(),
            "by_version": by_version,
            "recent_spans": self.spans.snapshot(),
        }


class ServingClient:
    """Blocking closed-loop client with reconnect + whole-request retry.

    ``act`` sends one observation and waits for ITS reply; any transport
    fault — connect refused, reset mid-flight, torn stream — drops the
    connection, backs off (jittered exponential), reconnects and resends
    the request whole.  A request is only lost when the deadline expires
    (``TimeoutError``), so "zero drops" is measurable client-side:
    every ``act`` call either returns, raises typed, or times out.
    """

    def __init__(self, host: str, port: int, *,
                 connect_timeout_s: float = 2.0,
                 io_timeout_s: float = 5.0, seed: int = 0,
                 max_frame: int = 64 << 20, trace: bool = False,
                 token: int = 0):
        self.host = host
        self.port = int(port)
        self._connect_timeout = float(connect_timeout_s)
        self._io_timeout = float(io_timeout_s)
        self._max_frame = int(max_frame)
        # Tracing needs the v2 hello (the flags byte lives in its
        # extension); a plain client keeps the anonymous v1 hello and the
        # bit-identical wire.  ``token`` rides the v2 hello so a traced
        # client can still talk to a run-token-locked fleet port.
        self.trace = bool(trace)
        self._token = int(token)
        self.spans = TraceSpanLog(depth=64)
        self._sock: Optional[socket.socket] = None
        self._parser = FrameParser(max_frame=max_frame)
        self._backoff = Backoff(base_s=0.05, max_s=1.0, seed=seed)
        self._req_id = 0
        self._out_seq = 0
        self.reconnects = 0
        self.retries = 0
        self.shed_seen = 0
        self._ever_connected = False

    # -- connection --------------------------------------------------------

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _ensure_connected(self) -> bool:
        if self._sock is not None:
            return True
        if not self._backoff.ready():
            return False
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self._connect_timeout
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(
                serve_hello_ext_bytes(0, 0, self._token,
                                      flags=HELLO_FLAG_TRACE)
                if self.trace else serve_hello_bytes()
            )
            sock.settimeout(self._io_timeout)
        except OSError:
            self._backoff.fail()
            return False
        self._sock = sock
        self._parser = FrameParser(max_frame=self._max_frame)
        self._out_seq = 0
        # NB: backoff resets on a verified REPLY (act), not here — a
        # router with zero healthy replicas accepts and closes instantly,
        # and resetting on connect would turn that into a tight loop.
        self.reconnects += int(self._ever_connected)
        self._ever_connected = True
        return True

    # -- request path ------------------------------------------------------

    def act(self, obs, timeout: float = 30.0,
            trace_id: int = 0) -> ServedAction:
        """One observation → one ServedAction, across reconnects.

        Raises :class:`ServerOverloaded` on a typed shed reply (counted
        on ``shed_seen`` — the caller decides whether to retry),
        :class:`ServingError` on other typed refusals, and
        ``TimeoutError`` when the deadline expires unanswered.
        ``trace_id`` rides the trace prefix on a trace-mode client."""
        t_start = time.monotonic()
        deadline = t_start + timeout
        first_try = True
        while time.monotonic() < deadline:
            if not self._ensure_connected():
                time.sleep(0.005)
                continue
            if not first_try:
                self.retries += 1
            first_try = False
            self._req_id += 1
            rid = self._req_id
            try:
                payload = encode_request(rid, obs)
                if self.trace:
                    payload = wrap_trace(trace_id, payload)
                self._out_seq += 1
                self._sock.sendall(
                    frame_bytes(F_SREQ, self._out_seq, [payload])
                )
                got = self._await_reply(rid, deadline)
            except (OSError, socket.timeout):
                self._drop()
                self._backoff.fail()
                continue
            if got is None:          # torn stream / stale reply: retry
                continue
            kind, payload = got
            if kind == F_SREP:
                self._backoff.reset()
                req_id, action, version, q = decode_reply(payload)
                self.spans.record(trace_id if self.trace else 0,
                                  "serve.request.client", t_start)
                return ServedAction(action, q, version,
                                    time.monotonic() - t_start)
            req_id, code, msg = decode_error(payload)
            if code == E_OVERLOADED:
                self._backoff.reset()   # transport fine; server is shedding
                self.shed_seen += 1
                raise ServerOverloaded(msg)
            if code == E_CLOSED:
                # Replica draining/shutting down: reconnect (the router
                # re-balances to a live one) rather than failing the call.
                self._drop()
                self._backoff.fail()
                continue
            raise ServingError(f"server error {code}: {msg}")
        raise TimeoutError(
            f"no reply within {timeout:.1f}s "
            f"(retries={self.retries}, reconnects={self.reconnects})"
        )

    def _await_reply(self, rid: int, deadline: float):
        """Frames until ``rid``'s reply (or None to force a retry after a
        dropped connection / torn stream)."""
        while True:
            got = self._parser.next()
            if got is not None:
                kind, payload = got
                if kind == F_SREP:
                    if decode_reply(payload)[0] == rid:
                        return kind, payload
                    continue              # stale reply from a retried req
                if kind == F_SERR:
                    req_id = decode_error(payload)[0]
                    if req_id in (rid, 0):
                        return kind, payload
                    continue
                # Unknown kind: protocol violation — treat as torn.
                self._drop()
                self._backoff.fail()
                return None
            if self._parser.error is not None:
                self._drop()
                self._backoff.fail()
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("deadline")
            self._sock.settimeout(min(self._io_timeout, remaining))
            data = self._sock.recv(_RECV_CHUNK)
            if not data:
                raise OSError("connection closed by peer")
            self._parser.feed(data)

    def close(self) -> None:
        self._drop()
