"""ape_x_dqn_tpu — a TPU-native Ape-X DQN framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of the reference
``lefarov/Ape-X-DQN`` (Distributed Prioritized Experience Replay, Horgan et
al. 2018): ε-ladder actor fleets, n-step double-Q learning, central
prioritized replay with a sum-tree, async actor∥replay∥learner pipeline, and
a data-parallel pjit learner over a TPU mesh.

Lazy by contract (PEP 562): importing this package must NOT import jax.
Child processes across the fleet — replay shard servers, remote worker
launchers, the by-path bench producers, the lint gate — import submodules
like ``ape_x_dqn_tpu.replay.service`` and live on sub-second spawns, and
``import ape_x_dqn_tpu.anything`` executes this file first.  An eager
``from .types import ...`` here taxed every one of them with the full
device-runtime import; the re-exports below resolve on first attribute
access instead (``from ape_x_dqn_tpu import TrainState`` still works).
The ``import-light`` checker in ``ape_x_dqn_tpu/analysis`` walks exactly
this chain.
"""

from __future__ import annotations

import importlib

__version__ = "0.1.0"

# name -> defining submodule, resolved on first attribute access.
_LAZY = {
    "NStepTransition": "ape_x_dqn_tpu.types",
    "PrioritizedBatch": "ape_x_dqn_tpu.types",
    "TrainState": "ape_x_dqn_tpu.types",
}

__all__ = [
    "NStepTransition",
    "PrioritizedBatch",
    "TrainState",
    "__version__",
]


def __getattr__(name):
    target = _LAZY.get(name)
    if target is not None:
        return getattr(importlib.import_module(target), name)
    # `ape_x_dqn_tpu.types` style submodule access after a bare
    # `import ape_x_dqn_tpu` — import it on demand.
    try:
        return importlib.import_module(f"{__name__}.{name}")
    except ModuleNotFoundError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
