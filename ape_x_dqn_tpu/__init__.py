"""ape_x_dqn_tpu — a TPU-native Ape-X DQN framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of the reference
``lefarov/Ape-X-DQN`` (Distributed Prioritized Experience Replay, Horgan et
al. 2018): ε-ladder actor fleets, n-step double-Q learning, central
prioritized replay with a sum-tree, async actor∥replay∥learner pipeline, and
a data-parallel pjit learner over a TPU mesh.
"""

from ape_x_dqn_tpu.types import (
    NStepTransition,
    PrioritizedBatch,
    TrainState,
)

__version__ = "0.1.0"

__all__ = [
    "NStepTransition",
    "PrioritizedBatch",
    "TrainState",
    "__version__",
]
