"""Sharded frame-dedup device replay: per-device dedup ring shards + the
fused K-step scan under ``shard_map`` — the configuration that makes
config3's 2M-slot replay FEASIBLE per chip (round-4 verdict item 1a:
2M × 84×84 dedup ≈ 16.5 GB global ≈ 4.2 GB/chip at dp=4, vs the
double-store's 28 GB that OOMed a 16 GB chip).

Structure mirrors replay/device_dp.py (the double-store sharded ring) with
one routing difference: transitions gather their frames BY REFERENCE, so a
transition must live on the same shard as its frames.  Chunks therefore
route WHOLE to one shard (the host stager pins each SOURCE to a shard —
carry refs resolve against the previous chunk of the same source, which
round-robin-by-chunk would scatter) instead of striping rows.  Each shard
keeps an independent frame-seq space; per-shard stratified PER with the
same realized-law IS correction as device_dp (shards contribute equally).

All state lives in global jax Arrays (NamedSharding over the mesh);
per-shard cursor/count/fcount ride along as [n]-shaped arrays.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ape_x_dqn_tpu.parallel.mesh import shard_map

from ape_x_dqn_tpu.replay.device import fused_scan_body
from ape_x_dqn_tpu.replay.device_dedup import (
    DedupDeviceReplayState,
    dedup_device_add_frames,
    dedup_device_add_transitions,
    dedup_sample_many,
)

_AXIS = "data"


def dedup_replay_specs() -> DedupDeviceReplayState:
    sh = P(_AXIS)
    return DedupDeviceReplayState(
        frames=sh, obs_ref=sh, next_ref=sh, action=sh, reward=sh,
        discount=sh, mass=sh, cursor=sh, count=sh, fcount=sh,
    )


def _local(state: DedupDeviceReplayState) -> DedupDeviceReplayState:
    return state.replace(
        cursor=state.cursor[0], count=state.count[0], fcount=state.fcount[0]
    )


def _packed(state: DedupDeviceReplayState) -> DedupDeviceReplayState:
    return state.replace(
        cursor=state.cursor[None], count=state.count[None],
        fcount=state.fcount[None],
    )


def init_sharded_dedup_replay(
    capacity: int,
    obs_shape,
    mesh: Mesh,
    frame_capacity: int | None = None,
    frame_ratio: float = 1.25,
    obs_dtype=jnp.uint8,
) -> DedupDeviceReplayState:
    n = mesh.shape[_AXIS]
    if frame_capacity is None:
        frame_capacity = max(n, int(round(capacity * frame_ratio)))
        frame_capacity -= frame_capacity % n
    if capacity % n or frame_capacity % n:
        raise ValueError(
            f"capacity {capacity} and frame_capacity {frame_capacity} must "
            f"divide by the data-axis extent {n} (per-device ring shards)"
        )
    sh = NamedSharding(mesh, P(_AXIS))

    def init():
        return DedupDeviceReplayState(
            frames=jnp.zeros((frame_capacity, *obs_shape), obs_dtype),
            obs_ref=jnp.zeros((capacity,), jnp.int32),
            next_ref=jnp.zeros((capacity,), jnp.int32),
            action=jnp.zeros((capacity,), jnp.int32),
            reward=jnp.zeros((capacity,), jnp.float32),
            discount=jnp.zeros((capacity,), jnp.float32),
            mass=jnp.zeros((capacity,), jnp.float32),
            cursor=jnp.zeros((n,), jnp.int32),
            count=jnp.zeros((n,), jnp.int32),
            fcount=jnp.zeros((n,), jnp.int32),
        )

    shardings = dedup_replay_specs()
    shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), shardings,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.jit(init, out_shardings=shardings)()


def shard_seq_modulus(frame_capacity: int, n: int) -> int:
    """The per-shard seq modulus the host stager must reduce refs by
    (each shard's LOCAL frame ring is frame_capacity / n)."""
    cf = frame_capacity // n
    return ((1 << 30) // cf) * cf


def build_sharded_dedup_add_frames(mesh: Mesh, jit: bool = True):
    """Per-shard frame-block ingest: ``frames`` is [n, B_f, *obs] with
    shard d consuming ITS OWN block frames[d] (chunks route whole to a
    shard — module docstring)."""
    specs = dedup_replay_specs()

    def add(state, frames):
        def body(st, fr):
            return _packed(dedup_device_add_frames(_local(st), fr[0]))

        return shard_map(
            body, mesh=mesh, in_specs=(specs, P(_AXIS)), out_specs=specs,
        )(state, frames)

    if jit:
        return jax.jit(add, donate_argnums=(0,))
    return add


def build_sharded_dedup_add_transitions(
    mesh: Mesh, priority_exponent: float = 0.6, jit: bool = True
):
    """Per-shard transition-block ingest (+ the liveness sweep, per
    shard): every leading-axis-[n] argument carries shard d's own block."""
    specs = dedup_replay_specs()

    def add(state, obs_ref, next_ref, action, reward, discount, priorities):
        def body(st, o, nx, a, r, d, p):
            return _packed(dedup_device_add_transitions(
                _local(st), o[0], nx[0], a[0], r[0], d[0], p[0],
                priority_exponent,
            ))

        row = P(_AXIS)
        return shard_map(
            body, mesh=mesh,
            in_specs=(specs, row, row, row, row, row, row),
            out_specs=specs,
        )(state, obs_ref, next_ref, action, reward, discount, priorities)

    if jit:
        return jax.jit(add, donate_argnums=(0,))
    return add


def build_sharded_dedup_fused_learn_step(
    train_step_fn,
    mesh: Mesh,
    batch_size: int,
    steps_per_call: int = 1,
    priority_exponent: float = 0.6,
    target_sync_freq: Optional[int] = 2500,
    sample_ahead: bool = False,
    jit: bool = True,
):
    """The sharded dedup twin of ``device_dp.build_sharded_fused_learn_step``
    — same contract (global batch, per-shard B/n sampling, grad all-reduce
    inside the scan via ``grad_reduce_axis="data"``), dedup gather."""
    n = mesh.shape[_AXIS]
    if batch_size % n:
        raise ValueError(
            f"batch_size {batch_size} must divide by the data-axis extent {n}"
        )
    B_local = batch_size // n
    K = steps_per_call
    specs = dedup_replay_specs()

    def body(train_state, replay_state, beta, rng):
        rng = jax.random.fold_in(rng, jax.lax.axis_index(_AXIS))
        train_state, r, metrics = fused_scan_body(
            train_step_fn, train_state, _local(replay_state), beta, rng,
            steps_per_call=K, batch_size=B_local,
            priority_exponent=priority_exponent,
            target_sync_freq=target_sync_freq, sample_ahead=sample_ahead,
            axis_name=_AXIS, sample_many_fn=dedup_sample_many,
        )
        return train_state, _packed(r), metrics

    from ape_x_dqn_tpu.learner.train_step import StepMetrics

    metrics_specs = StepMetrics(
        loss=P(), mean_abs_td=P(), max_abs_td=P(),
        priorities=P(None, _AXIS), mean_q=P(),
    )
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(), specs, P(), P()),
        out_specs=(P(), specs, metrics_specs),
    )
    if jit:
        return jax.jit(fn, donate_argnums=(0, 1))
    return fn
