"""Sharded device replay: per-device HBM ring shards + fused K-step scan
under ``shard_map`` — device replay and learner data parallelism COMBINED.

Round-3 verdict, top item: the fused HBM path (replay/device.py) and the
mesh learner (parallel/dp.py) were mutually exclusive, so no configuration
could scale the 4.5k single-chip steps/s by the device count — BASELINE
config 4's 50k steps/s had no code path.  This module is that path:

  * the replay ring shards over the mesh's ``data`` axis — each device owns
    ``capacity / n`` slots in ITS HBM and ingests ``1/n`` of every actor
    chunk (leading-axis contiguous split, so each shard keeps a
    time-ordered FIFO sub-stream and ring overwrite remains eviction);
  * each fused call runs the K-step [sample → train → restamp] scan on
    every device over its OWN shard, with the gradient all-reduce
    (``pmean`` over ICI) inside the scan body — the only cross-device
    traffic is 2·|params| per step, exactly what data-parallel training
    fundamentally requires; sampling and priority restamp never leave the
    owning device;
  * sampling is stratified PER *within* each shard (B/n rows per device).
    Shards contribute equally, so the realized sampling law is
    q_i = (mass_i / shard_total) / n; the IS weights correct for exactly
    that law (device_replay_sample_many's ``axis_name`` mode) with the
    global size and a global max-normalization (``psum``/``pmax``).  With
    uniform chunk striping the shard totals track each other and the law
    converges to the single-ring p_i = mass_i / total; the weights are
    exact for the actual law either way, so the estimator stays unbiased
    (the same per-shard-PER scheme distributed replay services use).

Reference mapping: this scales the reference's single learner hot loop
(reference learner.py:63-80) the way SURVEY §7 build stage 5 prescribes —
not by translating its manager RPCs, but by putting the whole
sample/train/restamp loop inside one SPMD program per device group.

All state lives in global jax Arrays (``NamedSharding`` over the mesh), so
checkpointing device_gets one global pytree; per-shard cursors/counts ride
along as ``[n]``-shaped arrays sharded over the same axis.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ape_x_dqn_tpu.parallel.mesh import shard_map

from ape_x_dqn_tpu.replay.device import (
    DeviceReplayState,
    device_replay_add,
    fused_scan_body,
)

_AXIS = "data"


def replay_specs() -> DeviceReplayState:
    """PartitionSpec pytree for the sharded replay state: every leaf —
    rings on their slot axis, per-shard cursor/count on their only axis —
    splits over ``data``."""
    sh = P(_AXIS)
    return DeviceReplayState(
        obs=sh, next_obs=sh, action=sh, reward=sh, discount=sh, mass=sh,
        cursor=sh, count=sh,
    )


def _local(state: DeviceReplayState) -> DeviceReplayState:
    """Inside shard_map: the [1]-shaped cursor/count block → the scalar
    spelling device.py's functions expect."""
    return state.replace(cursor=state.cursor[0], count=state.count[0])


def _packed(state: DeviceReplayState) -> DeviceReplayState:
    return state.replace(cursor=state.cursor[None], count=state.count[None])


def init_sharded_device_replay(
    capacity: int,
    obs_shape,
    mesh: Mesh,
    obs_dtype=jnp.uint8,
) -> DeviceReplayState:
    """Allocate the global ring, sharded over ``data`` at creation (zeros
    materialize directly on each device — no host-side ``capacity``-sized
    array ever exists)."""
    n = mesh.shape[_AXIS]
    if capacity % n:
        raise ValueError(
            f"replay capacity {capacity} must divide by the data-axis "
            f"extent {n} (per-device ring shards)"
        )
    sh = NamedSharding(mesh, P(_AXIS))

    def init():
        return DeviceReplayState(
            obs=jnp.zeros((capacity, *obs_shape), obs_dtype),
            next_obs=jnp.zeros((capacity, *obs_shape), obs_dtype),
            action=jnp.zeros((capacity,), jnp.int32),
            reward=jnp.zeros((capacity,), jnp.float32),
            discount=jnp.zeros((capacity,), jnp.float32),
            mass=jnp.zeros((capacity,), jnp.float32),
            cursor=jnp.zeros((n,), jnp.int32),
            count=jnp.zeros((n,), jnp.int32),
        )

    shardings = DeviceReplayState(
        obs=sh, next_obs=sh, action=sh, reward=sh, discount=sh, mass=sh,
        cursor=sh, count=sh,
    )
    return jax.jit(init, out_shardings=shardings)()


def build_sharded_replay_add(
    mesh: Mesh,
    priority_exponent: float = 0.6,
    jit: bool = True,
):
    """Sharded ingest: chunk rows split contiguously over ``data`` (row
    block d of M/n goes to shard d's ring).  Chunk length must divide by
    the axis extent — the host driver enforces block granularity."""
    specs = replay_specs()

    def add(state, chunk, priorities):
        def body(st, ch, pr):
            return _packed(
                device_replay_add(_local(st), ch, pr, priority_exponent)
            )

        return shard_map(
            body, mesh=mesh,
            in_specs=(specs, P(_AXIS), P(_AXIS)),
            out_specs=specs,
        )(state, chunk, priorities)

    if jit:
        return jax.jit(add, donate_argnums=(0,))
    return add


def build_sharded_fused_learn_step(
    train_step_fn,
    mesh: Mesh,
    batch_size: int,
    steps_per_call: int = 1,
    priority_exponent: float = 0.6,
    target_sync_freq: Optional[int] = 2500,
    sample_ahead: bool = False,
    jit: bool = True,
):
    """The sharded twin of ``device.build_fused_learn_step`` (ingest
    excluded — the runtime ingests on its own clock via the sharded add).

    Args mirror the unsharded builder; ``train_step_fn`` must be built with
    ``grad_reduce_axis="data"`` and ``sync_in_step=False`` so the gradient
    all-reduce happens inside the scan body and the target sync hoists to
    the call boundary.  ``batch_size`` is the GLOBAL batch; each shard
    samples ``batch_size / n`` rows from its own ring.

    Returns ``fn(train_state, replay_state, beta, rng) -> (train_state,
    replay_state, metrics)``; metrics leaves are stacked [K, ...] with
    ``priorities`` globally [K, batch_size] (sharded over ``data`` on the
    row axis); jitted with both states donated.
    """
    n = mesh.shape[_AXIS]
    if batch_size % n:
        raise ValueError(
            f"batch_size {batch_size} must divide by the data-axis extent {n}"
        )
    B_local = batch_size // n
    K = steps_per_call
    specs = replay_specs()

    def body(train_state, replay_state, beta, rng):
        # Per-shard sampling stream: every device must draw distinct rows
        # from its shard.
        rng = jax.random.fold_in(rng, jax.lax.axis_index(_AXIS))
        train_state, r, metrics = fused_scan_body(
            train_step_fn, train_state, _local(replay_state), beta, rng,
            steps_per_call=K, batch_size=B_local,
            priority_exponent=priority_exponent,
            target_sync_freq=target_sync_freq, sample_ahead=sample_ahead,
            axis_name=_AXIS,
        )
        return train_state, _packed(r), metrics

    # Metrics: scalars are pmean/pmax-reduced inside the train step →
    # replicated; per-row priorities (and sampled indices in sample-ahead
    # metrics) stay shard-local → global rows over ``data``.
    from ape_x_dqn_tpu.learner.train_step import StepMetrics

    metrics_specs = StepMetrics(
        loss=P(), mean_abs_td=P(), max_abs_td=P(),
        priorities=P(None, _AXIS), mean_q=P(),
    )
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(), specs, P(), P()),
        out_specs=(P(), specs, metrics_specs),
    )
    if jit:
        return jax.jit(fn, donate_argnums=(0, 1))
    return fn
