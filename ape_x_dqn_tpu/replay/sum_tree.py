"""Batched sum-tree for proportional prioritized sampling.

The reference's central replay keys priorities by string and renormalizes an
O(N) probability vector on every update (reference: replay.py:18-30), then
does an O(N·S) key-match scan per sample (replay.py:51-57).  BASELINE.json's
north-star asks for a sum-tree instead; this one is designed for the Ape-X
access pattern — *batched* writes (a whole actor chunk or learner batch of
priorities at once) and *batched* stratified sampling — so every operation is
a handful of vectorized numpy passes over tree levels, not Python-per-item
loops.

Layout: a flat array of ``2 * capacity`` float64 nodes (capacity rounded up to
a power of two).  Leaf ``i`` lives at ``capacity + i``; node ``k``'s children
are ``2k`` and ``2k+1``; ``tree[1]`` is the total mass.  float64 keeps the
prefix sums exact enough that stratified inverse-CDF descent never walks off
the populated region even after millions of updates.

A C++ twin of this structure lives in ``_native/sum_tree.cc`` (loaded via
ctypes by ``native.py``); this numpy version is the always-available fallback
and the reference implementation the native one is tested against.
"""

from __future__ import annotations

import numpy as np


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def stratified_targets(
    total: float, batch_size: int, rng: np.random.Generator
) -> np.ndarray:
    """One uniform draw per equal-mass stratum of [0, total), clamped below
    total against round-off.  Shared by the numpy and native trees so their
    stratified sampling stays bit-for-bit comparable."""
    if total <= 0:
        raise ValueError("cannot sample from an empty sum-tree")
    bounds = total / batch_size
    targets = (np.arange(batch_size) + rng.random(batch_size)) * bounds
    np.clip(targets, 0.0, np.nextafter(total, 0.0), out=targets)
    return targets


class SumTree:
    """Vectorized sum-tree over ``capacity`` slots.

    All methods accept/return numpy arrays and are O(B + log C) vectorized
    passes for a batch of B operations (each pass touches one tree level).
    Not thread-safe — callers (the replay buffer) hold the lock.
    """

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = int(capacity)
        self._leaf_base = _next_pow2(self.capacity)
        self._tree = np.zeros(2 * self._leaf_base, dtype=np.float64)
        self._depth = int(np.log2(self._leaf_base))

    @property
    def total(self) -> float:
        return float(self._tree[1])

    def get(self, indices: np.ndarray) -> np.ndarray:
        """Priorities at ``indices`` (int array) -> float64 array."""
        indices = np.asarray(indices, dtype=np.int64)
        return self._tree[self._leaf_base + indices]

    def max_priority(self) -> float:
        leaves = self._tree[self._leaf_base : self._leaf_base + self.capacity]
        return float(leaves.max()) if leaves.size else 0.0

    def set(self, indices: np.ndarray, priorities: np.ndarray) -> None:
        """Batched priority write + upward propagation.

        Duplicate indices are allowed; the *last* write wins (matching the
        reference's dict-upsert semantics at replay.py:32-42, minus its
        collapse bug).  Propagation recomputes parent = left + right along the
        affected paths, so duplicates cannot double-count.
        """
        indices = np.asarray(indices, dtype=np.int64)
        priorities = np.asarray(priorities, dtype=np.float64)
        if indices.size == 0:
            return
        if np.any((indices < 0) | (indices >= self.capacity)):
            raise IndexError("sum-tree index out of range")
        if np.any(priorities < 0) or not np.all(np.isfinite(priorities)):
            raise ValueError("priorities must be finite and non-negative")
        nodes = self._leaf_base + indices
        # Last-write-wins for duplicate indices: numpy fancy assignment already
        # applies writes in order, so later duplicates overwrite earlier ones.
        self._tree[nodes] = priorities
        # Propagate: at each level, recompute each affected parent from both
        # children (immune to duplicate-index double counting).
        parents = np.unique(nodes >> 1)
        while parents[0] >= 1:
            left = self._tree[2 * parents]
            right = self._tree[2 * parents + 1]
            self._tree[parents] = left + right
            if parents[0] == 1:
                break
            parents = np.unique(parents >> 1)

    def sample(self, targets: np.ndarray) -> np.ndarray:
        """Inverse-CDF lookup: for each target mass in [0, total), descend to
        the leaf whose prefix-sum interval contains it.  Fully vectorized —
        one comparison per tree level for the whole batch.
        """
        targets = np.asarray(targets, dtype=np.float64).copy()
        nodes = np.ones(targets.shape, dtype=np.int64)
        for _ in range(self._depth):
            left = 2 * nodes
            left_mass = self._tree[left]
            go_right = targets >= left_mass
            targets = np.where(go_right, targets - left_mass, targets)
            nodes = np.where(go_right, left + 1, left)
        leaf = nodes - self._leaf_base
        # Float round-off can land exactly on a zero-mass leaf edge; clamp to
        # the populated region.
        return np.clip(leaf, 0, self.capacity - 1)

    def sample_stratified(self, batch_size: int, rng: np.random.Generator) -> np.ndarray:
        """Stratified proportional sample: one draw per equal-mass stratum
        (lower variance than i.i.d. draws; standard PER practice)."""
        return self.sample(stratified_targets(self.total, batch_size, rng))
