"""ctypes bindings for the native C++ sum-tree core.

Compiles ``_native/sum_tree.cc`` with g++ on first use (cached .so next to
the source, keyed by source mtime) and exposes ``NativeSumTree`` with the
exact interface of the numpy ``SumTree`` — the replay buffer takes either via
its ``sum_tree_cls`` parameter.  If no compiler is available the import still
succeeds and ``native_available()`` returns False; callers fall back to numpy.

pybind11 is not in this image, so the boundary is a C ABI + ctypes — zero
copies (numpy arrays passed as raw pointers), no Python objects crossing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_HERE, "_native", "sum_tree.cc")
_SO = os.path.join(_HERE, "_native", "sum_tree.so")

_lib = None
_lib_err: str | None = None
_lock = threading.Lock()


def _build() -> None:
    # Compile to a private temp file, then atomically rename over the .so:
    # two processes racing on first use must never dlopen a half-written
    # artifact (rename is atomic within a directory on POSIX), and a failed
    # compile must not leave a bad .so that poisons every later run.
    tmp = f"{_SO}.tmp.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17",
        "-o", tmp, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.rename(tmp, _SO)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load():
    global _lib, _lib_err
    with _lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
            lib.st_create.restype = ctypes.c_void_p
            lib.st_create.argtypes = [ctypes.c_int64]
            lib.st_destroy.argtypes = [ctypes.c_void_p]
            lib.st_total.restype = ctypes.c_double
            lib.st_total.argtypes = [ctypes.c_void_p]
            lib.st_max.restype = ctypes.c_double
            lib.st_max.argtypes = [ctypes.c_void_p]
            lib.st_set.restype = ctypes.c_int32
            lib.st_set.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
            ]
            lib.st_get.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
            ]
            lib.st_sample.argtypes = [
                ctypes.c_void_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_int64),
            ]
            _lib = lib
        except Exception as e:  # compiler missing, build failure, load failure
            _lib_err = f"{type(e).__name__}: {e}"
        return _lib


def native_available() -> bool:
    return _load() is not None


def native_error() -> str | None:
    _load()
    return _lib_err


def _i64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f64(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


class NativeSumTree:
    """Drop-in replacement for ``sum_tree.SumTree`` backed by the C++ core."""

    def __init__(self, capacity: int):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native sum-tree unavailable: {_lib_err}")
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = int(capacity)
        self._lib = lib
        self._handle = lib.st_create(self.capacity)
        if not self._handle:
            raise MemoryError("st_create failed")

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.st_destroy(handle)
            self._handle = None

    @property
    def total(self) -> float:
        return float(self._lib.st_total(self._handle))

    def max_priority(self) -> float:
        return float(self._lib.st_max(self._handle))

    def get(self, indices: np.ndarray) -> np.ndarray:
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        out = np.empty(idx.shape[0], dtype=np.float64)
        self._lib.st_get(self._handle, idx.shape[0], _i64(idx), _f64(out))
        return out

    def set(self, indices: np.ndarray, priorities: np.ndarray) -> None:
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        pri = np.ascontiguousarray(priorities, dtype=np.float64)
        if idx.size == 0:
            return
        rc = self._lib.st_set(self._handle, idx.shape[0], _i64(idx), _f64(pri))
        if rc == -1:
            raise IndexError("sum-tree index out of range")
        if rc == -2:
            raise ValueError("priorities must be finite and non-negative")

    def sample(self, targets: np.ndarray) -> np.ndarray:
        tgt = np.ascontiguousarray(targets, dtype=np.float64)
        out = np.empty(tgt.shape[0], dtype=np.int64)
        self._lib.st_sample(self._handle, tgt.shape[0], _f64(tgt), _i64(out))
        return out

    def sample_stratified(self, batch_size: int, rng: np.random.Generator) -> np.ndarray:
        from ape_x_dqn_tpu.replay.sum_tree import stratified_targets

        return self.sample(stratified_targets(self.total, batch_size, rng))


def default_sum_tree_cls():
    """Native core when the toolchain allows, numpy otherwise."""
    if native_available():
        return NativeSumTree
    from ape_x_dqn_tpu.replay.sum_tree import SumTree

    return SumTree
