"""Two-tier frame store: hot DRAM span cache over a CRC-framed cold file.

ROADMAP item 6 ("break the DRAM wall on the frame ring"): the 2M-slot dedup
layout pins 17.6 GB of frames in one host's DRAM (BENCH_r06
``host_dedup_2m.frames_gb``) — capacity, not speed, is the binding
constraint on replay scale.  This module is the cold tier that decouples
them, the way external replay services (Reverb) decouple replay capacity
from learner memory:

  * **Spans** — the frame ring's slots are grouped into fixed spans of
    ``span_frames`` consecutive slots (~64 KiB by default).  A span is the
    unit of eviction and fault: big enough to amortize per-record framing
    and CRC, small enough that a stratified sample batch faults megabytes,
    not gigabytes.
  * **Hot tier** — a bounded dict of span-id → ndarray blocks.  DRAM held
    is exactly ``len(hot) × span_bytes``; everything else lives cold.
    Priority mass, the sum-tree, and all transition metadata stay hot in
    the owning replay — the sampling law and ``update_priorities`` are
    untouched by tiering (only the frame *bytes* move).
  * **Cold tier** — one sparse file of fixed record slots, TWO per span
    (A/B alternating by spill count), each record CRC-framed like an APXC
    chunk (magic | span id | length | crc32 over the payload).  pwrite to
    a stable offset; a SIGKILL mid-spill leaves a torn record that fails
    its CRC and is *detected*, never sampled (``ColdSpanCorrupt``).  The
    slot a checkpoint base references is PINNED at ``cold_refs()`` time:
    later re-spills only ever write the other slot, so the committed
    refs stay readable however often a span churns before the next base
    supersedes the pin set (older generations' refs are best-effort —
    a clobbered one fails typed and the fallback walk moves on).
  * **Eviction** — least-recently-*sampled* first (a monotone touch stamp
    bumped on every get/put), down to a low watermark once the hot tier
    crosses the high one.  Spilling a clean span (disk copy current) is
    free: drop the block.  The owning replay exposes ``spill_cold()`` and
    a ``TierEvictor`` thread calls it off the learner's critical path
    (runtime/async_pipeline — same discipline as the ingest stager and
    the checkpoint writer).
  * **Checkpoint refs** — ``cold_refs()`` describes every cold span as
    (span id, file offset, length, crc): an incremental base snapshot of
    a mostly-cold replay embeds its *hot* frames and references the cold
    ones by offset instead of re-reading them (utils/checkpoint_inc
    integration — checkpointing a 10M-slot replay must not page the cold
    tier back in).  Restore verifies each referenced record's CRC *and*
    its content CRC against the snapshot-time value: any mismatch is a
    typed ``ColdSpanCorrupt`` (a subclass of ``ChunkCorrupt``, so the
    checkpoint fallback walk handles it like any other bad chunk) —
    degraded restores are loud, never silently wrong.

Everything here is numpy + stdlib (no jax): kill-test children and
restore tooling import it for free.  All methods are called under the
owning replay's lock; the class itself adds no locking.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from typing import Optional

import numpy as np

from ape_x_dqn_tpu.utils.checkpoint_inc import ChunkCorrupt
from ape_x_dqn_tpu.utils.metrics import LatencyHistogram

_REC_MAGIC = b"APXS"
_REC_VERSION = 1
# magic 4s | u32 version | u64 span_id | u64 payload_len | u32 crc32(payload)
_REC_HDR = struct.Struct("<4sIQQI")

# Auto span sizing targets ~64 KiB payloads: big enough that record framing
# and python dispatch amortize, small enough that one 32-row sample batch
# faults at most a few MB.
_AUTO_SPAN_BYTES = 64 << 10


class ColdSpanCorrupt(ChunkCorrupt):
    """A cold span record failed its CRC / framing check (torn spill,
    bit rot, or a ref whose record was since rewritten past the A/B
    retention).  Subclasses ``ChunkCorrupt`` so the incremental-restore
    fallback walk (utils/checkpoint_inc) treats a bad cold ref exactly
    like a bad chunk file: walk back a rung or surface typed — never
    return recycled pixels as replay data."""

    def __init__(self, message: str, path: Optional[str] = None,
                 span: Optional[int] = None):
        super().__init__(message, path=path, generation=None, index=span)
        self.span = span


def auto_span_frames(frame_bytes: int) -> int:
    return max(1, _AUTO_SPAN_BYTES // max(1, int(frame_bytes)))


class ColdSpanStore:
    """The spill file: ``2 × n_spans`` fixed record slots (A/B per span),
    sparse until written.  Records are self-framed (header + CRC) so a
    torn write is detectable in isolation; readers address records by
    byte offset, which is what checkpoint cold refs carry."""

    def __init__(self, path: str, n_spans: int, max_payload: int):
        self.path = str(path)
        self.n_spans = int(n_spans)
        self.max_payload = int(max_payload)
        self.record_size = _REC_HDR.size + self.max_payload
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o644)
        # Sparse preallocation: disk blocks materialize per spilled span.
        # Grow-only — a reader opened with a smaller layout (restore
        # tooling addressing records by explicit offset) must never
        # truncate a live spill file.
        need = 2 * self.n_spans * self.record_size
        if os.fstat(self._fd).st_size < need:
            os.ftruncate(self._fd, need)

    def offset(self, sid: int, ab: int) -> int:
        return (2 * int(sid) + (int(ab) & 1)) * self.record_size

    def write(self, sid: int, ab: int, payload: bytes) -> tuple:
        """pwrite one record; returns (offset, crc32).  No fsync here —
        durability is only needed once a checkpoint references the
        record, and ``sync()`` covers that boundary."""
        if len(payload) > self.max_payload:
            raise ValueError("span payload exceeds record slot")
        crc = zlib.crc32(payload)
        hdr = _REC_HDR.pack(_REC_MAGIC, _REC_VERSION, int(sid),
                            len(payload), crc)
        off = self.offset(sid, ab)
        os.pwrite(self._fd, hdr + payload, off)
        return off, crc

    def read(self, offset: int, sid: Optional[int] = None,
             want_crc: Optional[int] = None) -> bytes:
        """Read + verify one record at ``offset``.  Raises
        ``ColdSpanCorrupt`` on any framing/CRC failure, on a span-id
        mismatch, and — when ``want_crc`` is given (checkpoint refs) —
        on content drift since the ref was taken."""
        hdr = os.pread(self._fd, _REC_HDR.size, int(offset))
        if len(hdr) < _REC_HDR.size:
            raise ColdSpanCorrupt(
                f"{self.path}@{offset}: truncated record header",
                path=self.path, span=sid,
            )
        magic, version, rec_sid, plen, crc = _REC_HDR.unpack(hdr)
        if magic != _REC_MAGIC or version != _REC_VERSION:
            raise ColdSpanCorrupt(
                f"{self.path}@{offset}: bad record magic/version "
                f"(never spilled, or torn)", path=self.path, span=sid,
            )
        if sid is not None and rec_sid != int(sid):
            raise ColdSpanCorrupt(
                f"{self.path}@{offset}: record is span {rec_sid}, "
                f"expected {sid}", path=self.path, span=sid,
            )
        if plen > self.max_payload:
            raise ColdSpanCorrupt(
                f"{self.path}@{offset}: payload length {plen} exceeds "
                f"record slot", path=self.path, span=sid,
            )
        payload = os.pread(self._fd, int(plen), int(offset) + _REC_HDR.size)
        if len(payload) != plen or zlib.crc32(payload) != crc:
            raise ColdSpanCorrupt(
                f"{self.path}@{offset}: crc mismatch (torn or corrupt "
                f"cold span)", path=self.path, span=sid,
            )
        if want_crc is not None and crc != int(want_crc):
            raise ColdSpanCorrupt(
                f"{self.path}@{offset}: span {rec_sid} content changed "
                f"since the checkpoint referenced it (crc {crc} != "
                f"{int(want_crc)})", path=self.path, span=sid,
            )
        return payload

    @property
    def fd(self) -> int:
        """The raw descriptor — the native core's batched fault path
        (rc_fault_batch) preads records directly from it."""
        return self._fd

    def sync(self) -> None:
        os.fsync(self._fd)

    def close(self, unlink: bool = False) -> None:
        if self._fd is not None:
            try:
                os.close(self._fd)
            finally:
                self._fd = None
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def __del__(self):
        try:
            if getattr(self, "_fd", None) is not None:
                os.close(self._fd)
                self._fd = None
        except OSError:
            pass


class TieredFrameRing:
    """Hot span cache + cold store presenting flat frame-slot addressing.

    Drop-in for the dense ``(capacity, *frame_shape)`` ndarray the host
    replays index: ``get``/``put`` take arbitrary slot indices,
    ``get_span``/``put_span`` take wrap-aware contiguous runs (ring
    cursor IO and checkpoint spans).  Reads of never-written slots return
    zeros, matching ndarray semantics, so a tiered replay is bit-exact
    with its dense twin from the first sample on.

    The owner's lock serializes every call; the evictor reaches eviction
    through the owner (``spill_cold``) under that same lock.
    """

    def __init__(self, capacity: int, frame_shape, dtype=np.uint8,
                 hot_budget_bytes: int = 0, spill_path: str = "",
                 span_frames: int = 0,
                 watermark_high: float = 1.0, watermark_low: float = 0.9):
        self.capacity = int(capacity)
        self.frame_shape = tuple(frame_shape)
        self.dtype = np.dtype(dtype)
        self.frame_bytes = int(np.prod(self.frame_shape)) * self.dtype.itemsize
        self.span_frames = int(span_frames) if span_frames else \
            auto_span_frames(self.frame_bytes)
        self.n_spans = -(-self.capacity // self.span_frames)
        self.span_bytes = self.span_frames * self.frame_bytes
        self.hot_budget_bytes = int(hot_budget_bytes)
        self.watermark_high = float(watermark_high)
        self.watermark_low = float(watermark_low)
        if not spill_path:
            raise ValueError("tiered ring needs a spill_path")
        self.store = ColdSpanStore(spill_path, self.n_spans, self.span_bytes)
        self._hot: dict = {}                  # sid -> ndarray block
        self._touch = np.zeros(self.n_spans, np.int64)
        self._clock = 0
        # Per-span cold record state: valid flag, which A/B slot holds the
        # current content, its crc, and the spill count (drives A/B).
        self._cold_valid = np.zeros(self.n_spans, bool)
        self._cold_ab = np.zeros(self.n_spans, np.int8)
        self._cold_crc = np.zeros(self.n_spans, np.uint32)
        self._spills = np.zeros(self.n_spans, np.int64)
        # A/B slot referenced by the newest checkpoint base (-1 = none):
        # spills never write a pinned slot, so the committed refs stay
        # valid however many times a span churns between bases.
        self._pinned_ab = np.full(self.n_spans, -1, np.int8)
        # Dirty = hot content newer than the cold record (or never spilled).
        self._dirty = np.zeros(self.n_spans, bool)
        # Counters (owner exposes via tier_stats; obs layer scrapes them).
        self.spilled_bytes = 0
        self.spill_writes = 0
        self.fault_reads = 0
        self.fault_bytes = 0
        self.fault_ms = LatencyHistogram(min_s=1e-5, max_s=60.0,
                                         per_decade=10)

    # -- span helpers ----------------------------------------------------

    def _span_len(self, sid: int) -> int:
        """Frames actually covered by span ``sid`` (the last span may be
        short when capacity % span_frames != 0)."""
        return min(self.span_frames,
                   self.capacity - sid * self.span_frames)

    def _tick(self, sid) -> None:
        self._clock += 1
        self._touch[sid] = self._clock

    def _block(self, sid: int) -> np.ndarray:
        """The hot block for ``sid``, faulting from cold if needed,
        zero-allocating if the span was never written."""
        blk = self._hot.get(sid)
        if blk is None:
            blk = self._fault(sid)
        return blk

    def _fault(self, sid: int) -> np.ndarray:
        n = self._span_len(sid)
        if self._cold_valid[sid]:
            t0 = time.perf_counter()
            payload = self.store.read(
                self.store.offset(sid, int(self._cold_ab[sid])),
                sid=sid, want_crc=int(self._cold_crc[sid]),
            )
            blk = np.frombuffer(payload, self.dtype).reshape(
                n, *self.frame_shape
            ).copy()
            self.fault_reads += 1
            self.fault_bytes += len(payload)
            self.fault_ms.record(time.perf_counter() - t0)
            self._dirty[sid] = False   # disk copy is current
        else:
            blk = np.zeros((n, *self.frame_shape), self.dtype)
            self._dirty[sid] = True    # nothing on disk yet
        self._hot[sid] = blk
        return blk

    # -- flat-index access (sample gather / scattered put) ---------------

    def get(self, idx: np.ndarray) -> np.ndarray:
        idx = np.asarray(idx, np.int64)
        out = np.empty((idx.shape[0], *self.frame_shape), self.dtype)
        sids = idx // self.span_frames
        for sid in np.unique(sids):
            sel = sids == sid
            blk = self._block(int(sid))
            out[sel] = blk[idx[sel] - int(sid) * self.span_frames]
            self._tick(int(sid))
        return out

    def put(self, idx: np.ndarray, frames) -> None:
        idx = np.asarray(idx, np.int64)
        frames = np.asarray(frames, self.dtype)
        sids = idx // self.span_frames
        for sid in np.unique(sids):
            sel = sids == sid
            blk = self._block(int(sid))
            blk[idx[sel] - int(sid) * self.span_frames] = frames[sel]
            self._dirty[sid] = True
            self._tick(int(sid))

    # -- wrap-aware contiguous runs (ring cursor IO, checkpoint spans) ---

    def get_span(self, start: int, n: int) -> np.ndarray:
        """n frames from ring slot ``start`` (wrap-aware)."""
        out = np.empty((n, *self.frame_shape), self.dtype)
        self._run(start, n, out, write=False)
        return out

    def put_span(self, start: int, n: int, frames) -> None:
        frames = np.ascontiguousarray(frames, self.dtype)
        self._run(start, n, frames, write=True)

    def _run(self, start: int, n: int, buf: np.ndarray, write: bool) -> None:
        start = int(start) % self.capacity
        done = 0
        while done < n:
            slot = (start + done) % self.capacity
            sid = slot // self.span_frames
            within = slot - sid * self.span_frames
            take = min(n - done, self._span_len(sid) - within)
            if write and within == 0 and take == self._span_len(sid) \
                    and sid not in self._hot:
                # Full-span overwrite of a non-resident span: no fault —
                # the old content is dead, allocate fresh.
                blk = np.empty((take, *self.frame_shape), self.dtype)
                self._hot[sid] = blk
            else:
                blk = self._block(sid)
            if write:
                blk[within:within + take] = buf[done:done + take]
                self._dirty[sid] = True
            else:
                buf[done:done + take] = blk[within:within + take]
            self._tick(sid)
            done += take

    # -- eviction --------------------------------------------------------

    @property
    def hot_bytes(self) -> int:
        return sum(b.nbytes for b in self._hot.values())

    @property
    def cold_bytes(self) -> int:
        """Bytes only the cold tier holds (cold-valid spans not resident)."""
        return sum(
            self._span_len(int(s)) * self.frame_bytes
            for s in np.nonzero(self._cold_valid)[0]
            if int(s) not in self._hot
        )

    def over_high_watermark(self) -> bool:
        return (self.hot_budget_bytes > 0 and
                self.hot_bytes > self.hot_budget_bytes * self.watermark_high)

    def spill(self, max_spans: int = 0, target_bytes: Optional[int] = None
              ) -> tuple:
        """Evict least-recently-touched hot spans until the hot tier is at
        or under ``target_bytes`` (default: low watermark × budget), or
        ``max_spans`` were spilled (0 = unbounded).  Returns
        (spans_spilled, bytes_written) — bytes_written counts only dirty
        spans (clean ones just drop their block)."""
        if target_bytes is None:
            target_bytes = int(self.hot_budget_bytes * self.watermark_low)
        spilled = wrote = 0
        if not self._hot:
            return 0, 0
        order = sorted(self._hot, key=lambda s: self._touch[s])
        for sid in order:
            if self.hot_bytes <= target_bytes:
                break
            wrote += self._evict_one(sid)
            spilled += 1
            if max_spans and spilled >= max_spans:
                break
        return spilled, wrote

    def flush_dirty(self) -> int:
        """Write every dirty hot span's cold record WITHOUT dropping
        residency — after this, any eviction is a free clean drop (and a
        kill loses no span that was hot at flush time).  Returns bytes
        written."""
        wrote = 0
        for sid, blk in self._hot.items():
            if not self._dirty[sid]:
                continue
            ab = self._next_ab(sid)
            payload = np.ascontiguousarray(blk).tobytes()
            _, crc = self.store.write(sid, ab, payload)
            self._spills[sid] += 1
            self._cold_ab[sid] = ab
            self._cold_crc[sid] = np.uint32(crc)
            self._cold_valid[sid] = True
            self._dirty[sid] = False
            self.spilled_bytes += len(payload)
            self.spill_writes += 1
            wrote += len(payload)
        return wrote

    def _evict_one(self, sid: int) -> int:
        blk = self._hot.pop(sid)
        if not self._dirty[sid] and self._cold_valid[sid]:
            return 0  # disk copy current — eviction is free
        ab = self._next_ab(sid)
        payload = np.ascontiguousarray(blk).tobytes()
        _, crc = self.store.write(sid, ab, payload)
        self._spills[sid] += 1
        self._cold_ab[sid] = ab
        self._cold_crc[sid] = np.uint32(crc)
        self._cold_valid[sid] = True
        self._dirty[sid] = False
        self.spilled_bytes += len(payload)
        self.spill_writes += 1
        return len(payload)

    # -- checkpoint integration (utils/checkpoint_inc) -------------------

    def _next_ab(self, sid: int) -> int:
        """The record slot the next spill of ``sid`` may write: never the
        slot the newest checkpoint base references (pinned at cold_refs
        time), else plain A/B alternation — a committed base's refs stay
        readable however often the span churns before the next base."""
        pinned = int(self._pinned_ab[sid])
        if pinned >= 0:
            return pinned ^ 1
        return int(self._spills[sid] + 1) & 1

    def cold_refs(self, nf: int) -> Optional[dict]:
        """Offset references for every span that is cold right now, and the
        hot remainder inline — the base-snapshot split.  ``nf`` bounds the
        written region (slots >= nf were never written; their spans are
        skipped entirely).  Returns None when nothing is cold (the caller
        keeps the legacy dense format).  fsyncs the store first: a
        manifest must never reference a record the disk hasn't seen."""
        written = -(-int(nf) // self.span_frames) if nf else 0
        cold = [s for s in range(written)
                if s not in self._hot and self._cold_valid[s]]
        if not cold:
            return None
        self.store.sync()
        # Pin the about-to-be-referenced records: spills now avoid
        # these slots until the next base supersedes the pin set.
        self._pinned_ab[:] = -1
        for sid_ in cold:
            self._pinned_ab[sid_] = self._cold_ab[sid_]
        hot = [s for s in range(written) if s not in cold]
        hot_frames = (
            np.concatenate([self._span_block_copy(s) for s in hot])
            if hot else np.zeros((0, *self.frame_shape), self.dtype)
        )
        return {
            "tier_span_frames": np.asarray([self.span_frames], np.int64),
            "tier_capacity": np.asarray([self.capacity], np.int64),
            "tier_hot_sids": np.asarray(hot, np.int64),
            "tier_hot_frames": hot_frames,
            "tier_cold_sids": np.asarray(cold, np.int64),
            "tier_cold_offsets": np.asarray(
                [self.store.offset(s, int(self._cold_ab[s])) for s in cold],
                np.int64),
            "tier_cold_lens": np.asarray(
                [self._span_len(s) for s in cold], np.int64),
            "tier_cold_crcs": np.asarray(
                [int(self._cold_crc[s]) for s in cold], np.int64),
            "tier_spill_path": np.frombuffer(
                self.store.path.encode(), np.uint8).copy(),
        }

    def _span_block_copy(self, sid: int) -> np.ndarray:
        blk = self._hot.get(sid)
        if blk is not None:
            return np.array(blk, copy=True)
        return self.get_span(sid * self.span_frames, self._span_len(sid))

    def adopt_cold_ref(self, sid: int, offset: int, length: int,
                       crc: int, src: "ColdSpanStore") -> None:
        """Restore-side: take ownership of one cold span.  Same store +
        same layout → verify the record in place and mark the span cold
        without copying a byte (the O(hot) restore).  Different store →
        read (verified) and install hot; the evictor re-spills later."""
        same = (os.path.realpath(src.path)
                == os.path.realpath(self.store.path)
                and src.record_size == self.store.record_size)
        if same:
            # Verify, then reference in place.
            src.read(offset, sid=sid, want_crc=crc)
            ab = (int(offset) // self.store.record_size) & 1
            self._hot.pop(sid, None)
            self._cold_valid[sid] = True
            self._cold_ab[sid] = ab
            self._cold_crc[sid] = np.uint32(int(crc) & 0xFFFFFFFF)
            # Keep future A/B alternation away from the adopted slot.
            self._spills[sid] = ab
            # The restored chain still references this record — pin it
            # until the next base supersedes the set.
            self._pinned_ab[sid] = ab
            self._dirty[sid] = False
            return
        payload = src.read(offset, sid=sid, want_crc=crc)
        blk = np.frombuffer(payload, self.dtype).reshape(
            int(length), *self.frame_shape).copy()
        self._hot[sid] = blk
        self._cold_valid[sid] = False
        self._dirty[sid] = True
        self._tick(sid)

    def drop_all(self) -> None:
        """Full-restore preamble: forget every tier state (the snapshot
        about to load defines the new contents)."""
        self._hot.clear()
        self._cold_valid[:] = False
        self._dirty[:] = False
        self._pinned_ab[:] = -1
        self._touch[:] = 0

    # -- stats / lifecycle ------------------------------------------------

    def tier_stats(self) -> dict:
        out = {
            "hot_bytes": self.hot_bytes,
            "hot_spans": len(self._hot),
            "cold_spans": int(np.count_nonzero(self._cold_valid)),
            "hot_budget_bytes": self.hot_budget_bytes,
            "span_frames": self.span_frames,
            "spilled_bytes": self.spilled_bytes,
            "spill_writes": self.spill_writes,
            "fault_reads": self.fault_reads,
            "fault_bytes": self.fault_bytes,
        }
        out["fault_ms"] = self.fault_ms.summary()  # keys already in ms
        return out

    def close(self, unlink: bool = False) -> None:
        self.store.close(unlink=unlink)


def read_cold_refs_dense(state: dict) -> np.ndarray:
    """Materialize a cold-ref base snapshot's full frame region [0, nf)
    as one dense array — the restore path for replays WITHOUT a tier (or
    with an incompatible layout).  Every referenced record is CRC- and
    content-verified; failures raise ``ColdSpanCorrupt`` so the
    checkpoint fallback walk can act on them."""
    span_frames = int(np.asarray(state["tier_span_frames"]).reshape(-1)[0])
    capacity = int(np.asarray(state["tier_capacity"]).reshape(-1)[0])
    path = bytes(np.asarray(state["tier_spill_path"], np.uint8)).decode()
    hot_sids = np.asarray(state["tier_hot_sids"], np.int64)
    cold_sids = np.asarray(state["tier_cold_sids"], np.int64)
    cold_offsets = np.asarray(state["tier_cold_offsets"], np.int64)
    cold_lens = np.asarray(state["tier_cold_lens"], np.int64)
    cold_crcs = np.asarray(state["tier_cold_crcs"], np.int64)
    hot_frames = np.asarray(state["tier_hot_frames"])
    frame_shape = hot_frames.shape[1:]
    if not len(frame_shape):
        raise ColdSpanCorrupt("tiered base has no frame shape witness",
                              path=path)
    sids = list(hot_sids) + list(cold_sids)
    written = (max(int(s) for s in sids) + 1) * span_frames if sids else 0
    nf = min(written, capacity)
    dense = np.zeros((nf, *frame_shape), hot_frames.dtype)

    def span_len(sid):
        return min(span_frames, capacity - sid * span_frames)

    off = 0
    for sid in hot_sids:
        n = span_len(int(sid))
        lo = int(sid) * span_frames
        dense[lo:lo + min(n, nf - lo)] = hot_frames[off:off + n][:nf - lo]
        off += n
    if len(cold_sids):
        store = ColdSpanStore(
            path, int(max(cold_sids)) + 1,
            span_frames * int(np.prod(frame_shape))
            * hot_frames.dtype.itemsize,
        )
        try:
            for sid, offset, length, crc in zip(
                    cold_sids, cold_offsets, cold_lens, cold_crcs):
                payload = store.read(int(offset), sid=int(sid),
                                     want_crc=int(crc))
                blk = np.frombuffer(payload, hot_frames.dtype).reshape(
                    int(length), *frame_shape)
                lo = int(sid) * span_frames
                dense[lo:lo + min(int(length), nf - lo)] = blk[:nf - lo]
        finally:
            store.close()
    return dense


class SpanTierIndex:
    """Tier bookkeeping for a ring whose hot storage lives ELSEWHERE —
    the native core's address-stable frame mmap.  Same span states,
    LRU, cold store, counters, and checkpoint-ref format as
    ``TieredFrameRing``; instead of owning hot blocks it drives three
    callables against the external storage:

      read_fn(fstart_slot, n)  -> ndarray   (wrap-aware copy, no drop)
      evict_fn(fstart_slot, n) -> ndarray   (copy out + release pages —
                                             rc_evict_span: the mmap's
                                             region MADV_DONTNEEDs)
      fault_fn(fstart_slot, n, frames)      (copy verified bytes back —
                                             rc_fault_span)

    A span is *resident* (counts toward hot bytes) once written or
    faulted; evicting drops residency and the RSS with it.  All calls
    run under the owning replay's lock.
    """

    def __init__(self, capacity: int, frame_shape, dtype,
                 hot_budget_bytes: int, spill_path: str,
                 read_fn, evict_fn, fault_fn,
                 span_frames: int = 0,
                 watermark_high: float = 1.0, watermark_low: float = 0.9,
                 fault_batch_fn=None, drop_fn=None):
        self.capacity = int(capacity)
        self.frame_shape = tuple(frame_shape)
        self.dtype = np.dtype(dtype)
        self.frame_bytes = int(np.prod(self.frame_shape)) * self.dtype.itemsize
        self.span_frames = int(span_frames) if span_frames else \
            auto_span_frames(self.frame_bytes)
        self.n_spans = -(-self.capacity // self.span_frames)
        self.span_bytes = self.span_frames * self.frame_bytes
        self.hot_budget_bytes = int(hot_budget_bytes)
        self.watermark_high = float(watermark_high)
        self.watermark_low = float(watermark_low)
        self.store = ColdSpanStore(spill_path, self.n_spans, self.span_bytes)
        self._read, self._evict, self._fault_in = read_fn, evict_fn, fault_fn
        # Optional fast paths (the native core provides both):
        # fault_batch_fn(fd, offsets, fstarts, lens, sids, want_crcs) -> i
        # preads + CRC-verifies + installs a whole batch in ONE
        # GIL-released call (-1 = all ok, else first failing index);
        # drop_fn(fstart, n) releases a CLEAN span's pages without the
        # copy-out rc_evict_span would do.
        self._fault_batch = fault_batch_fn
        self._drop = drop_fn
        self._n_resident = 0
        self._resident = np.zeros(self.n_spans, bool)
        self._dirty = np.zeros(self.n_spans, bool)
        self._cold_valid = np.zeros(self.n_spans, bool)
        self._cold_ab = np.zeros(self.n_spans, np.int8)
        self._cold_crc = np.zeros(self.n_spans, np.uint32)
        self._spills = np.zeros(self.n_spans, np.int64)
        # Checkpoint-referenced A/B slots (see TieredFrameRing): spills
        # never write a pinned slot.
        self._pinned_ab = np.full(self.n_spans, -1, np.int8)
        self._touch = np.zeros(self.n_spans, np.int64)
        self._clock = 0
        self.spilled_bytes = 0
        self.spill_writes = 0
        self.fault_reads = 0
        self.fault_bytes = 0
        self.fault_ms = LatencyHistogram(min_s=1e-5, max_s=60.0,
                                         per_decade=10)

    def _span_len(self, sid: int) -> int:
        return min(self.span_frames,
                   self.capacity - sid * self.span_frames)

    def _tick(self, sid) -> None:
        self._clock += 1
        self._touch[sid] = self._clock

    def spans_of_slots(self, slots: np.ndarray) -> np.ndarray:
        return np.unique(np.asarray(slots, np.int64) // self.span_frames)

    def spans_of_run(self, start: int, n: int) -> np.ndarray:
        """Span ids overlapped by the wrap-aware run [start, start+n)."""
        if n <= 0:
            return np.zeros(0, np.int64)
        start = int(start) % self.capacity
        if start + n <= self.capacity:
            return np.arange(start // self.span_frames,
                             (start + n - 1) // self.span_frames + 1)
        head = np.arange(start // self.span_frames, self.n_spans)
        tail = np.arange(0, (start + n - self.capacity - 1)
                         // self.span_frames + 1)
        return np.unique(np.concatenate([head, tail]))

    def _set_resident(self, sid: int, value: bool) -> None:
        if bool(self._resident[sid]) != value:
            self._resident[sid] = value
            self._n_resident += 1 if value else -1

    def ensure_hot(self, sids) -> None:
        """Fault every cold span in ``sids`` back into the external
        storage (the pre-gather / pre-export step).  Never-spilled,
        non-resident spans are zeros in the mmap already — nothing to do
        beyond marking them resident on first touch.  With the native
        fast path the whole batch lands in ONE GIL-released pread+CRC
        call; a failure falls back to the per-span python read, whose
        error carries the full typed diagnosis."""
        sids = np.asarray(sids, np.int64)
        self._clock += 1
        self._touch[sids] = self._clock
        need_arr = sids[~self._resident[sids]]
        if not need_arr.size:
            return
        if self._fault_batch is not None:
            cold_arr = need_arr[self._cold_valid[need_arr]]
            if cold_arr.size:
                t0 = time.perf_counter()
                offsets = (2 * cold_arr
                           + self._cold_ab[cold_arr]) \
                    * self.store.record_size
                fstarts = cold_arr * self.span_frames
                lens = np.minimum(self.span_frames,
                                  self.capacity - fstarts)
                crcs = self._cold_crc[cold_arr].astype(np.int64)
                bad = self._fault_batch(
                    self.store.fd, np.ascontiguousarray(offsets),
                    np.ascontiguousarray(fstarts),
                    np.ascontiguousarray(lens),
                    np.ascontiguousarray(cold_arr), crcs,
                )
                if bad >= 0:
                    # Re-read the failing span through the python path:
                    # same verification, full typed diagnosis.
                    s = int(cold_arr[int(bad)])
                    self.store.read(int(offsets[bad]), sid=s,
                                    want_crc=int(crcs[bad]))
                    raise ColdSpanCorrupt(
                        f"{self.store.path}: span {s} failed the batched "
                        "fault but verified alone (concurrent rewrite?)",
                        path=self.store.path, span=s,
                    )
                self.fault_reads += int(cold_arr.size)
                self.fault_bytes += int(lens.sum()) * self.frame_bytes
                self.fault_ms.record(time.perf_counter() - t0)
                self._dirty[cold_arr] = False
            self._n_resident += int(
                np.count_nonzero(~self._resident[need_arr])
            )
            self._resident[need_arr] = True
            self._trim_clean_inline(exclude=need_arr)
            return
        for sid in [int(s) for s in need_arr]:
            if self._cold_valid[sid]:
                t0 = time.perf_counter()
                payload = self.store.read(
                    self.store.offset(sid, int(self._cold_ab[sid])),
                    sid=sid, want_crc=int(self._cold_crc[sid]),
                )
                blk = np.frombuffer(payload, self.dtype).reshape(
                    self._span_len(sid), *self.frame_shape)
                self._fault_in(sid * self.span_frames, blk.shape[0], blk)
                self.fault_reads += 1
                self.fault_bytes += len(payload)
                self.fault_ms.record(time.perf_counter() - t0)
                self._dirty[sid] = False
            self._set_resident(sid, True)

    def _trim_clean_inline(self, exclude: np.ndarray) -> None:
        """Keep the budget tight WITHOUT cross-thread lock ping-pong: a
        fault batch that pushed the hot tier over its high watermark
        drops the least-recently-sampled CLEAN spans (disk record
        current — a drop is one madvise, ~10 us) right here, excluding
        the spans this batch just faulted.  Dirty spans are never
        touched: their write-back stays on the evictor thread (the
        learner-critical-path contract covers WRITES, not page drops)."""
        if self._drop is None or self.hot_budget_bytes <= 0:
            return
        if self.hot_bytes <= self.hot_budget_bytes * self.watermark_high:
            return
        droppable = self._resident & self._cold_valid & ~self._dirty
        droppable[exclude] = False
        cand = np.nonzero(droppable)[0]
        if not cand.size:
            return
        target = int(self.hot_budget_bytes * self.watermark_low)
        excess_spans = max(
            0, -(-(self.hot_bytes - target) // self.span_bytes)
        )
        for sid in cand[np.argsort(self._touch[cand])][:excess_spans]:
            sid = int(sid)
            self._drop(sid * self.span_frames, self._span_len(sid))
            self._set_resident(sid, False)

    def note_write(self, start: int, n: int) -> None:
        """Pre-ingest hook for the wrap-aware run about to be written:
        cold spans only PARTIALLY covered must fault first (their
        untouched slots' content lives only in the cold record); fully
        covered spans skip the fault — their content is being replaced
        wholesale.  Afterwards every overlapped span is resident+dirty."""
        sids = self.spans_of_run(start, n)
        if not sids.size:
            return
        start = int(start) % self.capacity
        end = start + int(n)
        for sid in sids:
            sid = int(sid)
            lo = sid * self.span_frames
            hi = lo + self._span_len(sid)
            covered = (
                (start <= lo and end >= hi)
                or (end > self.capacity
                    and (end - self.capacity) >= hi)  # wrapped tail
            )
            if not covered and not self._resident[sid] \
                    and self._cold_valid[sid]:
                self.ensure_hot([sid])
            self._set_resident(sid, True)
            self._dirty[sid] = True
            self._tick(sid)

    @property
    def hot_bytes(self) -> int:
        return self._n_resident * self.span_bytes

    @property
    def cold_bytes(self) -> int:
        return sum(
            self._span_len(int(s)) * self.frame_bytes
            for s in np.nonzero(self._cold_valid & ~self._resident)[0]
        )

    def over_high_watermark(self) -> bool:
        return (self.hot_budget_bytes > 0 and
                self.hot_bytes > self.hot_budget_bytes * self.watermark_high)

    def spill(self, max_spans: int = 0,
              target_bytes: Optional[int] = None) -> tuple:
        if target_bytes is None:
            target_bytes = int(self.hot_budget_bytes * self.watermark_low)
        resident = np.nonzero(self._resident)[0]
        if not resident.size:
            return 0, 0
        order = resident[np.argsort(self._touch[resident])]
        spilled = wrote = 0
        for sid in order:
            if self.hot_bytes <= target_bytes:
                break
            sid = int(sid)
            n = self._span_len(sid)
            if not self._dirty[sid] and self._cold_valid[sid] \
                    and self._drop is not None:
                # Clean drop: disk record current — release pages only.
                self._drop(sid * self.span_frames, n)
            else:
                blk = self._evict(sid * self.span_frames, n)
                if self._dirty[sid] or not self._cold_valid[sid]:
                    ab = self._next_ab(sid)
                    payload = np.ascontiguousarray(blk).tobytes()
                    _, crc = self.store.write(sid, ab, payload)
                    self._spills[sid] += 1
                    self._cold_ab[sid] = ab
                    self._cold_crc[sid] = np.uint32(crc)
                    self._cold_valid[sid] = True
                    self.spilled_bytes += len(payload)
                    self.spill_writes += 1
                    wrote += len(payload)
            self._dirty[sid] = False
            self._set_resident(sid, False)
            spilled += 1
            if max_spans and spilled >= max_spans:
                break
        return spilled, wrote

    def flush_dirty(self) -> int:
        """Write every dirty resident span's record without dropping
        residency — evictions afterwards are clean drops."""
        wrote = 0
        for sid in np.nonzero(self._resident & self._dirty)[0]:
            sid = int(sid)
            n = self._span_len(sid)
            blk = self._read(sid * self.span_frames, n)
            ab = self._next_ab(sid)
            payload = np.ascontiguousarray(blk).tobytes()
            _, crc = self.store.write(sid, ab, payload)
            self._spills[sid] += 1
            self._cold_ab[sid] = ab
            self._cold_crc[sid] = np.uint32(crc)
            self._cold_valid[sid] = True
            self._dirty[sid] = False
            self.spilled_bytes += len(payload)
            self.spill_writes += 1
            wrote += len(payload)
        return wrote

    # -- checkpoint integration (same dict format as TieredFrameRing) ----

    def _next_ab(self, sid: int) -> int:
        """The record slot the next spill of ``sid`` may write: never the
        slot the newest checkpoint base references (pinned at cold_refs
        time), else plain A/B alternation — a committed base's refs stay
        readable however often the span churns before the next base."""
        pinned = int(self._pinned_ab[sid])
        if pinned >= 0:
            return pinned ^ 1
        return int(self._spills[sid] + 1) & 1

    def cold_refs(self, nf: int) -> Optional[dict]:
        written = -(-int(nf) // self.span_frames) if nf else 0
        cold = [s for s in range(written)
                if not self._resident[s] and self._cold_valid[s]]
        if not cold:
            return None
        self.store.sync()
        # Pin the about-to-be-referenced records: spills now avoid
        # these slots until the next base supersedes the pin set.
        self._pinned_ab[:] = -1
        for sid_ in cold:
            self._pinned_ab[sid_] = self._cold_ab[sid_]
        hot = [s for s in range(written) if s not in set(cold)]
        hot_frames = (
            np.concatenate([
                self._read(s * self.span_frames, self._span_len(s))
                for s in hot
            ])
            if hot else np.zeros((0, *self.frame_shape), self.dtype)
        )
        return {
            "tier_span_frames": np.asarray([self.span_frames], np.int64),
            "tier_capacity": np.asarray([self.capacity], np.int64),
            "tier_hot_sids": np.asarray(hot, np.int64),
            "tier_hot_frames": hot_frames,
            "tier_cold_sids": np.asarray(cold, np.int64),
            "tier_cold_offsets": np.asarray(
                [self.store.offset(s, int(self._cold_ab[s])) for s in cold],
                np.int64),
            "tier_cold_lens": np.asarray(
                [self._span_len(s) for s in cold], np.int64),
            "tier_cold_crcs": np.asarray(
                [int(self._cold_crc[s]) for s in cold], np.int64),
            "tier_spill_path": np.frombuffer(
                self.store.path.encode(), np.uint8).copy(),
        }

    def install_hot(self, sid: int, frames: np.ndarray) -> None:
        """Restore-side: place one span's frames into the external
        storage and account it resident+dirty."""
        blk = np.ascontiguousarray(frames, self.dtype)
        self._fault_in(sid * self.span_frames, blk.shape[0], blk)
        self._set_resident(sid, True)
        self._dirty[sid] = True
        self._tick(sid)

    def adopt_cold_ref(self, sid: int, offset: int, length: int,
                       crc: int, src: "ColdSpanStore") -> None:
        same = (os.path.realpath(src.path)
                == os.path.realpath(self.store.path)
                and src.record_size == self.store.record_size)
        if same:
            src.read(offset, sid=sid, want_crc=crc)
            # Stale mmap bytes for this span drop now; the next access
            # faults the verified record in.
            if self._drop is not None:
                self._drop(sid * self.span_frames, self._span_len(sid))
            else:
                self._evict(sid * self.span_frames, self._span_len(sid))
            ab = (int(offset) // self.store.record_size) & 1
            self._set_resident(sid, False)
            self._cold_valid[sid] = True
            self._cold_ab[sid] = ab
            self._cold_crc[sid] = np.uint32(int(crc) & 0xFFFFFFFF)
            self._spills[sid] = ab
            # The restored chain still references this record — pin it
            # until the next base supersedes the set.
            self._pinned_ab[sid] = ab
            self._dirty[sid] = False
            return
        payload = src.read(offset, sid=sid, want_crc=crc)
        blk = np.frombuffer(payload, self.dtype).reshape(
            int(length), *self.frame_shape)
        self._fault_in(sid * self.span_frames, blk.shape[0], blk)
        self._set_resident(sid, True)
        self._cold_valid[sid] = False
        self._dirty[sid] = True
        self._tick(sid)

    def drop_all(self) -> None:
        self._resident[:] = False
        self._n_resident = 0
        self._cold_valid[:] = False
        self._dirty[:] = False
        self._pinned_ab[:] = -1
        self._touch[:] = 0

    def tier_stats(self) -> dict:
        out = {
            "hot_bytes": self.hot_bytes,
            "hot_spans": self._n_resident,
            "cold_spans": int(np.count_nonzero(self._cold_valid)),
            "hot_budget_bytes": self.hot_budget_bytes,
            "span_frames": self.span_frames,
            "spilled_bytes": self.spilled_bytes,
            "spill_writes": self.spill_writes,
            "fault_reads": self.fault_reads,
            "fault_bytes": self.fault_bytes,
        }
        out["fault_ms"] = self.fault_ms.summary()
        return out

    def close(self, unlink: bool = False) -> None:
        self.store.close(unlink=unlink)


class TierEvictor(threading.Thread):
    """Background eviction — the stager/writer-thread pattern applied to
    the cold tier: the learner thread never pays for a spill; it only
    faults what it samples.  Wakes on a short cadence, spills in bounded
    batches (each batch is one replay-lock acquisition) whenever the ring
    is over its high watermark."""

    def __init__(self, replay, poll_s: float = 0.05,
                 batch_spans: int = 32):
        super().__init__(name="tier-evictor", daemon=True)
        self._replay = replay
        self._poll_s = float(poll_s)
        self._batch = int(batch_spans)
        # NB: not `_stop` — threading.Thread owns that name internally.
        self._halt = threading.Event()
        self.heartbeat = time.monotonic()
        self.error: Optional[BaseException] = None

    def stop(self, timeout: float = 10.0) -> None:
        self._halt.set()
        self.join(timeout=timeout)

    def run(self) -> None:
        try:
            while not self._halt.is_set():
                self.heartbeat = time.monotonic()
                if self._replay.tier_over_watermark():
                    self._replay.spill_cold(max_spans=self._batch)
                else:
                    self._halt.wait(self._poll_s)
        except BaseException as e:  # noqa: BLE001 — surfaced by the owner
            self.error = e
