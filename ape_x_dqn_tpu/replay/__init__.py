"""Replay subsystem: sum-tree priorities + prioritized ring-buffer stores
(double-store, frame-dedup, and their HBM device twins)."""

from ape_x_dqn_tpu.replay.buffer import PrioritizedReplay
from ape_x_dqn_tpu.replay.dedup import DedupReplay
from ape_x_dqn_tpu.replay.sum_tree import SumTree

__all__ = ["DedupReplay", "PrioritizedReplay", "SumTree"]
