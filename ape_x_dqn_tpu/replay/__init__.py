"""Replay subsystem: sum-tree priorities + prioritized ring-buffer stores
(double-store, frame-dedup, and their HBM device twins).

Lazy by contract (PEP 562): ``replay.service`` hosts the shard-server
path that spawns as a no-jax subprocess (``python -m
ape_x_dqn_tpu.replay.service``), and importing it executes this file
first.  ``buffer``/``dedup`` reach ``types`` (jax) at module scope, so
eager re-exports here put the whole device runtime on every shard spawn;
the names below resolve on first attribute access instead (enforced by
the ``import-light`` checker).
"""

from __future__ import annotations

import importlib

_LAZY = {
    "PrioritizedReplay": "ape_x_dqn_tpu.replay.buffer",
    "DedupReplay": "ape_x_dqn_tpu.replay.dedup",
    "SumTree": "ape_x_dqn_tpu.replay.sum_tree",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    target = _LAZY.get(name)
    if target is not None:
        return getattr(importlib.import_module(target), name)
    try:
        return importlib.import_module(f"{__name__}.{name}")
    except ModuleNotFoundError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
