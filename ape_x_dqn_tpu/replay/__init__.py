"""Replay subsystem: sum-tree priorities + prioritized ring-buffer store."""

from ape_x_dqn_tpu.replay.buffer import PrioritizedReplay
from ape_x_dqn_tpu.replay.sum_tree import SumTree

__all__ = ["PrioritizedReplay", "SumTree"]
