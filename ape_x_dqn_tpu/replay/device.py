"""Device-resident prioritized replay — sample, train, and restamp in-graph.

The host replay (replay/buffer.py) re-ships a frame batch host→device on
every learner step.  This module keeps the whole buffer in HBM as a pytree of
jax arrays, so after an actor chunk crosses the PCIe/tunnel boundary *once*,
everything else — ring insert, stratified prioritized sampling, IS weights,
the train step, and the priority write-back — runs inside XLA programs with
zero further transfers.  ``build_fused_learn_step`` goes further and fuses
ingest + K train steps into ONE dispatch (`lax.scan` over sampled batches),
amortizing host dispatch overhead — the single-chip path to the north-star
steps/sec (SURVEY §7 hard parts #1-2 collapse into on-device ops).

Sampling is flat prefix-sum inverse-CDF, not a tree: on TPU a cumsum over
the priority vector is one bandwidth-bound pass that the VPU eats (and the
pallas kernel in ops/pallas/sampling.py does it without materializing the
prefix array); an O(log N) pointer-chasing tree would serialize on exactly
the hardware that hates it.  Same math as the host sum-tree: mass ∝ p^α,
stratified targets, β-annealed IS weights (reference replay.py:24-30
semantics, reference defects excluded per SURVEY §2.8).

All mutating functions are functional (state in, state out) and meant to be
jitted with donation so ring writes happen in place in HBM.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ape_x_dqn_tpu.ops.pallas.sampling import sample_indices
from ape_x_dqn_tpu.types import NStepTransition, PrioritizedBatch


@struct.dataclass
class DeviceReplayState:
    obs: jax.Array          # uint8 [C, *obs_shape]
    next_obs: jax.Array     # uint8 [C, *obs_shape]
    action: jax.Array       # int32 [C]
    reward: jax.Array       # float32 [C]
    discount: jax.Array     # float32 [C]
    mass: jax.Array         # float32 [C] — p^α, 0 marks an empty slot
    cursor: jax.Array       # int32 []
    count: jax.Array        # int32 [] — total ever added (saturating view: size = min(count, C))

    @property
    def capacity(self) -> int:
        return self.mass.shape[0]


def init_device_replay(capacity: int, obs_shape, obs_dtype=jnp.uint8) -> DeviceReplayState:
    return DeviceReplayState(
        obs=jnp.zeros((capacity, *obs_shape), obs_dtype),
        next_obs=jnp.zeros((capacity, *obs_shape), obs_dtype),
        action=jnp.zeros((capacity,), jnp.int32),
        reward=jnp.zeros((capacity,), jnp.float32),
        discount=jnp.zeros((capacity,), jnp.float32),
        mass=jnp.zeros((capacity,), jnp.float32),
        cursor=jnp.zeros((), jnp.int32),
        count=jnp.zeros((), jnp.int32),
    )


def device_replay_add(
    state: DeviceReplayState,
    transitions: NStepTransition,
    priorities: jax.Array,
    priority_exponent: float = 0.6,
) -> DeviceReplayState:
    """Ring-insert a chunk (batch M static).  FIFO overwrite == eviction,
    and the slot's mass is replaced — no stale-priority leak."""
    M = priorities.shape[0]
    if M > state.capacity:
        # A chunk wider than the ring would wrap idx onto itself, and XLA
        # scatter with duplicate indices has unspecified write order —
        # silent ring corruption.  Static shapes make this a build-time
        # check (mirrors PrioritizedReplay.add's host-side guard).
        raise ValueError(
            f"chunk of {M} transitions exceeds replay capacity {state.capacity}"
        )
    idx = (state.cursor + jnp.arange(M, dtype=jnp.int32)) % state.capacity
    mass = jnp.power(jnp.maximum(priorities.astype(jnp.float32), 1e-12),
                     priority_exponent)
    return state.replace(
        obs=state.obs.at[idx].set(transitions.obs),
        next_obs=state.next_obs.at[idx].set(transitions.next_obs),
        action=state.action.at[idx].set(transitions.action.astype(jnp.int32)),
        reward=state.reward.at[idx].set(transitions.reward),
        discount=state.discount.at[idx].set(transitions.discount),
        mass=state.mass.at[idx].set(mass),
        cursor=(state.cursor + M) % state.capacity,
        count=state.count + M,
    )


def device_replay_sample(
    state: DeviceReplayState,
    rng: jax.Array,
    batch_size: int,
    beta: jax.Array | float = 0.4,
    axis_name: str | None = None,
) -> PrioritizedBatch:
    """Stratified proportional sample with IS weights, fully on device.

    The K=1 case of ``device_replay_sample_many`` (single implementation —
    the strict-PER path and the sample-ahead path cannot diverge)."""
    batch = device_replay_sample_many(state, rng, 1, batch_size, beta, axis_name)
    return jax.tree_util.tree_map(lambda a: a[0], batch)


def device_replay_sample_many(
    state: DeviceReplayState,
    rng: jax.Array,
    num_batches: int,
    batch_size: int,
    beta: jax.Array | float = 0.4,
    axis_name: str | None = None,
) -> PrioritizedBatch:
    """Sample K stratified batches from the *current* priorities in one
    batched inverse-CDF call + one row gather (leaves get leading [K, B]).

    The per-step spelling costs ~95 µs/step at B=32 on a v5e — almost all
    fixed op overhead, not bandwidth (PROFILE.md) — because a 32-row sample
    launches ~15 tiny ops.  Batching all K batches into one call amortizes
    that overhead K-fold.  Memory: the gather materializes all K batches —
    K·B·2·obs_bytes of transient HBM (K=2048, B=32, 84×84×1 ≈ 0.9 GB;
    frame-stacked 84×84×4 ≈ 3.7 GB) — so size K to the observation shape;
    the strict path holds one batch at a time.  The trade: batches 2..K are
    drawn from priorities
    as of call entry rather than after each preceding step's restamp — K
    steps of staleness, the same order the async Ape-X pipeline already
    tolerates between actor-priority computation and learner restamp
    (reference's actors/learner run fully desynchronized).

    ``axis_name``: when called per-shard inside ``shard_map`` (replay/
    device_dp.py — each device samples ``batch_size`` rows from its OWN
    ring shard), the IS weights must correct for the *actual* sampling
    law: row i of shard s is drawn with q_i = (mass_i / shard_total) / n
    (shards contribute equally, proportional within a shard), so
    w_i = (N_global · q_i)^-β, normalized by the **global** batch max
    (``pmax`` over the axis).  With ``None`` this reduces to the
    single-ring law exactly.
    """
    K, B = num_batches, batch_size
    total = jnp.sum(state.mass)
    bounds = total / B
    u = jax.random.uniform(rng, (K, B))
    targets = (jnp.arange(B, dtype=jnp.float32)[None, :] + u) * bounds
    targets = jnp.minimum(targets, total * (1.0 - 1e-7))
    idx = sample_indices(state.mass, targets.reshape(-1))      # [K*B]
    size_i = jnp.maximum(jnp.minimum(state.count, state.capacity), 1)
    idx = jnp.minimum(idx, size_i - 1)  # zero-mass guard (see sample above)
    probs = state.mass[idx] / jnp.maximum(total, 1e-12)
    if axis_name is None:
        n_shards = 1
        size_global = size_i
    else:
        n_shards = jax.lax.psum(1, axis_name)
        size_global = jax.lax.psum(size_i, axis_name)
    weights = jnp.power(
        jnp.maximum(size_global.astype(jnp.float32) * probs / n_shards, 1e-12),
        -beta,
    ).reshape(K, B)
    wmax = jnp.max(weights, axis=1, keepdims=True)
    if axis_name is not None:
        wmax = jax.lax.pmax(wmax, axis_name)
    weights = weights / wmax
    idx2 = idx.reshape(K, B)
    return PrioritizedBatch(
        transition=NStepTransition(
            obs=state.obs[idx].reshape(K, B, *state.obs.shape[1:]),
            action=state.action[idx2],
            reward=state.reward[idx2],
            discount=state.discount[idx2],
            next_obs=state.next_obs[idx].reshape(K, B, *state.next_obs.shape[1:]),
        ),
        indices=idx2,
        is_weights=weights.astype(jnp.float32),
    )


def device_replay_restamp_last(
    state: DeviceReplayState,
    indices: jax.Array,     # int32 [K, B] in step order
    priorities: jax.Array,  # float32 [K, B]
    priority_exponent: float = 0.6,
) -> DeviceReplayState:
    """Batched priority restamp with sequential (last-wins) semantics.

    A slot sampled by several of the K batches must end with the *latest*
    step's priority — what K in-scan scatters would produce.  XLA scatter
    leaves duplicate-index write order unspecified, so resolve duplicates
    first: stable-sort by slot (ties keep step order), keep only each run's
    last element, and route the rest to a dummy slot that is sliced off.
    One sort + one scatter replaces K 32-element scatters (~15 µs/step of
    pure op overhead, PROFILE.md).
    """
    idx = indices.reshape(-1)
    mass = jnp.power(
        jnp.maximum(priorities.astype(jnp.float32).reshape(-1), 1e-12),
        priority_exponent,
    )
    order = jnp.argsort(idx, stable=True)
    si, sm = idx[order], mass[order]
    is_last = jnp.concatenate(
        [si[1:] != si[:-1], jnp.ones((1,), bool)]
    )
    target = jnp.where(is_last, si, state.capacity)  # dummy slot C
    ext = jnp.concatenate([state.mass, jnp.zeros((1,), jnp.float32)])
    ext = ext.at[target].set(sm)
    return state.replace(mass=ext[:-1])


def device_replay_update_priorities(
    state: DeviceReplayState,
    indices: jax.Array,
    priorities: jax.Array,
    priority_exponent: float = 0.6,
) -> DeviceReplayState:
    mass = jnp.power(jnp.maximum(priorities.astype(jnp.float32), 1e-12),
                     priority_exponent)
    return state.replace(mass=state.mass.at[indices].set(mass))


def fused_scan_body(
    train_step_fn,
    train_state,
    replay_state: DeviceReplayState,
    beta,
    rng: jax.Array,
    *,
    steps_per_call: int,
    batch_size: int,
    priority_exponent: float,
    target_sync_freq: int | None,
    sample_ahead: bool,
    axis_name: str | None = None,
    sample_many_fn=None,
):
    """The K-step [sample → train → restamp] scan + hoisted target sync —
    the ONE body shared by the single-device builder below, the sharded
    builder (replay/device_dp.py, where it runs per shard inside shard_map
    with ``axis_name="data"`` and a per-shard batch size), and the
    frame-dedup layouts (replay/device_dedup.py, which inject their
    sampler via ``sample_many_fn``; restamp/update only touch ``.mass``,
    which every layout carries)."""
    K, B = steps_per_call, batch_size
    step_before = train_state.step
    if sample_many_fn is None:
        sample_many_fn = device_replay_sample_many

    if sample_ahead:
        batches = sample_many_fn(
            replay_state, rng, K, B, beta, axis_name
        )

        def body_pre(t_state, batch):
            t_state, metrics = train_step_fn(t_state, batch)
            return t_state, metrics

        train_state, metrics = jax.lax.scan(body_pre, train_state, batches)
        replay_state = device_replay_restamp_last(
            replay_state, batches.indices, metrics.priorities,
            priority_exponent,
        )
    else:

        def body(carry, step_rng):
            t_state, r_state = carry
            batch = jax.tree_util.tree_map(
                lambda a: a[0],
                sample_many_fn(r_state, step_rng, 1, B, beta, axis_name),
            )
            t_state, metrics = train_step_fn(t_state, batch)
            r_state = device_replay_update_priorities(
                r_state, batch.indices, metrics.priorities, priority_exponent
            )
            return (t_state, r_state), metrics

        rngs = jax.random.split(rng, K)
        (train_state, replay_state), metrics = jax.lax.scan(
            body, (train_state, replay_state), rngs
        )
    if target_sync_freq is not None:
        crossed = (train_state.step // target_sync_freq) > (
            step_before // target_sync_freq
        )
        train_state = train_state.replace(
            target_params=jax.tree_util.tree_map(
                lambda online, target: jnp.where(
                    crossed, online.astype(target.dtype), target
                ),
                train_state.params,
                train_state.target_params,
            )
        )
    return train_state, replay_state, metrics


def build_fused_learn_step(
    train_step_fn,
    batch_size: int,
    steps_per_call: int = 1,
    priority_exponent: float = 0.6,
    target_sync_freq: int | None = 2500,
    include_ingest: bool = True,
    sample_ahead: bool = False,
    jit: bool = True,
):
    """Fuse [ingest chunk] → scan_K [sample → train → restamp] into one
    XLA program.

    Args:
      train_step_fn: the *unjitted* fused train step
        (``build_train_step(..., jit=False)``).  When ``target_sync_freq``
        is set here, build it with ``sync_in_step=False`` — the per-step
        target-pytree rewrite costs ~95 µs/step on a v5e and is pure waste
        between the every-``freq``-step syncs.
      batch_size: replay sample size per learner step (static).
      steps_per_call: K learner steps per dispatch; host overhead amortizes
        by K (the chunk ingest happens once per call).
      target_sync_freq: hoisted target sync — after the K-step scan, copy
        online → target params iff the scan crossed a multiple of ``freq``.
        Exact when ``freq % K == 0`` (the crossing lands on a call
        boundary); otherwise the sync lands at the first boundary after the
        crossing, ≤ K−1 steps late — noise next to Ape-X's 2500-step
        staleness.  ``None`` = the train step handles sync itself
        (``sync_in_step=True``).

      include_ingest: with True (default) each call ingests one chunk
        before the scan — one dispatch total, the bench/bulk path, and the
        overlapped pipeline's folded-ingest dispatch
        (``FusedDeviceLearner.train_with_ingest`` builds this variant and
        rides one full ``ingest_block`` inside each fused call; the add is
        sequenced before the scan in the same program, so it is bit-for-bit
        identical to a separate ``device_replay_add`` dispatch — pinned by
        tests/test_pipeline_overlap.py).  With
        False the signature drops ``chunk``/``chunk_priorities`` and the
        caller ingests at its own cadence via ``device_replay_add`` — the
        async runtime's shape, where actor chunks arrive on their own clock.
      sample_ahead: with True, all K batches are sampled + gathered in ONE
        batched call from call-entry priorities and restamps are applied as
        one batched last-wins scatter after the scan — ~95 µs/step of fixed
        op overhead drops to ~µs (PROFILE.md).  Batches 2..K see priorities
        up to K steps stale (see ``device_replay_sample_many``); with False,
        each scan step samples/restamps against live priorities (the strict
        sequential-PER mode, also the test oracle for this one).

    Returns ``fn(train_state, replay_state, chunk, chunk_priorities, beta,
    rng) -> (train_state, replay_state, metrics)`` (without the chunk args
    when ``include_ingest=False``) with metrics stacked [K, ...]; jitted
    with both states donated.
    """

    def fused(train_state, replay_state, chunk, chunk_priorities, beta, rng):
        if include_ingest:
            replay_state = device_replay_add(
                replay_state, chunk, chunk_priorities, priority_exponent
            )
        return fused_scan_body(
            train_step_fn, train_state, replay_state, beta, rng,
            steps_per_call=steps_per_call, batch_size=batch_size,
            priority_exponent=priority_exponent,
            target_sync_freq=target_sync_freq, sample_ahead=sample_ahead,
        )

    if not include_ingest:
        inner = fused

        def fused_no_ingest(train_state, replay_state, beta, rng):
            return inner(train_state, replay_state, None, None, beta, rng)

        fused = fused_no_ingest

    if jit:
        return jax.jit(fused, donate_argnums=(0, 1))
    return fused
