"""Replay as a service — a fault-tolerant sharded replay fleet.

Horgan et al. 2018 is explicit that the CENTRAL REPLAY is the scaling
bottleneck of Ape-X; every landed piece of this repo (CRC-framed net
transport, tiered replay, delta param fan-out, the supervisor policy
tier) stops one step short of the architecture's actual shape: N learner
processes sampling one shared replay fleet.  What was missing is the
robustness layer that makes a REMOTE replay usable — today a learner's
sample path cannot survive its replay process dying, because the replay
lives in the learner's address space.  This module is that layer:

  * **Shard servers** (:class:`ReplayShardServer`): a replay-hosting
    process speaking framed RPCs (``sample`` / ``add`` /
    ``update_priorities`` / ``state_digest`` / ``stats``) over the
    runtime/net.py frame discipline (``u32 len | u32 crc | i64 seq |
    u8 kind``).  Torn, bitflipped, oversize and out-of-seq frames are
    counted and NEVER decoded — the connection retires, exactly the
    experience plane's adversarial-decode contract.  Ingest is
    dedup-aware: add/sample bodies are F_XPB-encoded (in-window frame
    dedup + negotiated zlib — ``encode_xpb_payload``), so PR 10's
    0.63 KB/transition wire economy carries through to the replay RPC.
  * **Sharding by slot range**: the global slot space ``[0, capacity)``
    splits into equal ranges, one plain :class:`PrioritizedReplay` per
    shard; clients map local↔global by the shard's base offset, adds
    route round-robin over healthy shards, priority updates route by
    ``index // shard_capacity``.
  * **Retrying clients** (:class:`ShardClient` per shard,
    :class:`ShardedReplayClient` over the fleet): per-request deadline,
    jittered exponential backoff, whole-request retry across reconnects
    with the ServingClient discipline (backoff resets ONLY on a verified
    reply), and graceful degradation — while a shard is down the learner
    keeps sampling/adding against the survivors, priority write-backs to
    the dead shard buffer last-write-wins and flush on recovery, and the
    failure surface is the typed :class:`ReplayShardUnavailable` plus a
    degraded ``replay_svc`` health component, never a wedge.
  * **At-most-once adds**: every logical ``add`` carries one req_id for
    its whole retry span; the shard remembers each client's last applied
    add and answers a retried duplicate from cache WITHOUT re-applying
    (the lost-reply shape — chaos ``rpc_drop_rate`` — cannot double-count
    experience on a shard).  Re-routing an add to a DIFFERENT shard after
    a deadline is at-least-once across the fleet by design: a duplicated
    experience chunk is harmless to replay, a lost one is the loss
    Ape-X already tolerates.
  * **Supervision + recovery** (:class:`ReplayServiceFleet`): shard
    processes respawn under the supervisor's RespawnPolicy arithmetic
    (exponential backoff + jitter + crash-loop quarantine), each
    incarnation recovers from the shard's own incremental checkpoint
    chain (``utils/checkpoint_inc`` — corruption walks back through the
    existing fallback rungs), announces itself with a fresh incarnation
    number, and the fleet rewrites the endpoints file atomically so
    clients re-resolve moved shards.  A mid-run SIGKILL therefore yields
    bit-exact-or-typed recovery: the respawned shard's ``state_digest``
    equals the committed chain's content crc, or the restore is a typed
    ``degraded_restore`` — never silently wrong samples.

Hello handshake (one struct each way, before any framing state):

    client → shard:  4s "APXV" | u32 version | i64 client_id | i64
                     shard_id | i64 incarnation | i64 token | u8 codec
    shard  → client: 4s "APXA" | u32 version | i64 shard_id | i64
                     incarnation | i64 capacity | i64 count

A hello with the wrong magic/version/shard_id/token — or a STALE
incarnation (the client pinning an incarnation that has since respawned)
— is rejected by closing before the ack, counted on ``stale_rejects`` /
``bad_hellos``; the client re-resolves the endpoint and reconnects.
``incarnation = -1`` in the hello means "current" (the normal client
mode; the ack tells the client what it connected to).

Import-light by design (stdlib + numpy + the shm_ring/net codecs): a
shard process never needs jax, so a fleet spawns in well under a second
per shard.
"""

from __future__ import annotations

import collections
import json
import os
import secrets
import select
import shutil
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ape_x_dqn_tpu.fleet.registry import (
    FleetAnnouncer,
    FleetClient,
    member_doc,
    member_id_for,
)

from ape_x_dqn_tpu.runtime.net import (
    CODEC_OFF,
    CODEC_ZLIB,
    F_RERR,
    F_RREP,
    F_RREQ,
    HELLO_FLAG_TRACE,
    RSVC_ACK_MAGIC,
    RSVC_MAGIC,
    Backoff,
    FrameParser,
    decode_xpb_payload,
    encode_xpb_payload,
    frame_bytes,
    split_trace,
    wrap_trace,
)
from ape_x_dqn_tpu.obs.lineage import BucketExemplars, TraceSpanLog
from ape_x_dqn_tpu.runtime.shm_ring import XP, decode_chunk, encode_chunk_parts
from ape_x_dqn_tpu.utils.metrics import LatencyHistogram

RSVC_VERSION = 1
# magic, version, client_id, shard_id, incarnation, token, codec, flags
# (flags was a pad byte — a pre-flags client packs 0 there, so the old
# hello reads as flags=0 and the wire stays bit-identical; bit 0 =
# HELLO_FLAG_TRACE negotiates the per-request trace prefix).
RSVC_HELLO = struct.Struct("<4sIqqqqBB6x")
# magic, version, shard_id, incarnation, capacity, count
RSVC_ACK = struct.Struct("<4sIqqqq")

# RPC ops.
OP_SAMPLE = 1
OP_ADD = 2
OP_UPDATE = 3
OP_DIGEST = 4
OP_STATS = 5
_OP_NAMES = {OP_SAMPLE: "sample", OP_ADD: "add", OP_UPDATE: "update",
             OP_DIGEST: "digest", OP_STATS: "stats"}

# Typed refusal codes (F_RERR payloads).
RE_BAD_REQUEST = 1   # well-framed but undecodable/ill-shaped request
RE_EMPTY = 2         # sample against an empty shard
RE_CLOSED = 3        # shard shutting down
RE_INTERNAL = 4      # op raised; the exception type rides the message

_RPC = struct.Struct("<QB7x")        # request head: req_id, op
_RREP = struct.Struct("<QBB6x")      # reply head: req_id, op, flags
_RERR = struct.Struct("<QH6x")       # error head: req_id, code | message
FLAG_DUP = 1                         # add reply served from the dedup cache
_SAMPLE_REQ = struct.Struct("<I4xdQ")   # batch_size, beta, sample seed
_SAMPLE_REP = struct.Struct("<dq")      # shard total p^α mass, shard size
_DIGEST_REQ = struct.Struct("<B7x")     # with_crc flag
# count, cursor, size, incarnation, capacity, total_mass, crc
_DIGEST_REP = struct.Struct("<qqqqqdI4x")

# "auto" proposes the zlib capability at the hello (like the experience
# plane's net_codec=auto); whether a given SAMPLE reply actually
# compresses is the shard's per-reply decision, gated on observed socket
# backpressure — see ReplayShardServer._reply_codec.
_CODEC_IDS = {"off": CODEC_OFF, "zlib": CODEC_ZLIB, "auto": CODEC_ZLIB}
_RECV_CHUNK = 1 << 16
_DEFAULT_MAX_FRAME = 64 << 20
# service_codec=auto: raw sample replies again after this many
# backpressure-free reply flushes (NetWriter's _AUTO_OFF_FLUSHES twin).
_AUTO_OFF_REPLIES = 256


class ReplayShardUnavailable(RuntimeError):
    """A replay RPC could not be served within its deadline — the shard
    (or, from :class:`ShardedReplayClient`, every shard) is down.  The
    typed degradation signal: callers route around it, buffer against it,
    or surface it; nothing ever silently samples wrong data."""

    def __init__(self, message: str, shard_id: Optional[int] = None,
                 op: Optional[str] = None):
        super().__init__(message)
        self.shard_id = shard_id
        self.op = op


class ReplayRpcError(RuntimeError):
    """A typed F_RERR refusal from a shard (bad request / empty /
    internal) — the request WAS answered; this is not unavailability."""

    def __init__(self, code: int, message: str):
        super().__init__(f"replay rpc error {code}: {message}")
        self.code = code


# ---------------------------------------------------------------------------
# RPC body codec: numpy dicts ride the APXT record format wrapped in the
# wire-efficiency container (F_XPB: in-window frame dedup + negotiated
# zlib).  One record per body; ``obs``/``next_obs`` uint8 leaves are
# exactly what the dedup encoder's span walk targets, so n-step overlap
# inside an add chunk ships each frame once — the 0.63 KB/transition
# economy, carried through to the replay plane.
# ---------------------------------------------------------------------------


def encode_body(arrays: Dict[str, np.ndarray], codec: int = CODEC_OFF,
                dedup: bool = True) -> bytes:
    rec = b"".join(
        bytes(p) if isinstance(p, (bytes, bytearray)) else memoryview(p)
        .cast("B").tobytes()
        for p in encode_chunk_parts(XP, 0, 0, arrays)
    )
    payload, _st = encode_xpb_payload([rec], codec=codec, dedup=dedup)
    return payload


def decode_body(payload, allow_zlib: bool = True,
                max_bytes: int = _DEFAULT_MAX_FRAME) -> Dict[str, np.ndarray]:
    """Arrays from one verified RPC body.  Raises ValueError on ANY
    malformation (bad codec, out-of-window dedup ref, truncated tables,
    short APXT buffers) — the caller counts torn / replies typed."""
    recs = decode_xpb_payload(payload, allow_zlib=allow_zlib,
                              max_bytes=max_bytes)
    if len(recs) != 1:
        raise ValueError(f"rpc body: expected 1 record, got {len(recs)}")
    return decode_chunk(recs[0], copy=True)[8]


class _Transition:
    """Attribute shim matching the replay's batch surface (obs/action/
    reward/discount/next_obs) without importing the jax-typed
    NStepTransition into shard processes."""

    __slots__ = ("obs", "action", "reward", "discount", "next_obs")

    def __init__(self, arrays: Dict[str, np.ndarray]):
        for k in self.__slots__:
            setattr(self, k, arrays[k])


# ---------------------------------------------------------------------------
# Shard server.
# ---------------------------------------------------------------------------


class _RConn:
    __slots__ = ("sock", "parser", "hello", "client_id", "codec", "flags",
                 "outbox", "out_off", "out_seq", "bytes_in", "bytes_out")

    def __init__(self, sock: socket.socket, max_frame: int):
        self.sock = sock
        self.parser = FrameParser(max_frame=max_frame)
        self.hello = bytearray()
        self.client_id: Optional[int] = None   # None until the ack went out
        self.codec = CODEC_OFF
        self.flags = 0
        self.outbox: collections.deque = collections.deque()
        self.out_off = 0
        self.out_seq = 0
        self.bytes_in = 0
        self.bytes_out = 0


class ReplayShardServer:
    """One replay shard: a PrioritizedReplay behind a framed-RPC socket
    front, with its own incremental checkpoint chain.

    A single pump thread runs accept + hello + parse + execute + reply in
    a select loop (replay ops are host-memory array work — there is no
    compute tier to batch behind, so inline execution IS the latency
    floor; one slow op delays the loop exactly as long as the op takes).
    The wall-cadence checkpoint save rides the same thread, so snapshots
    and mutations are serialized by construction.
    """

    def __init__(self, replay, shard_id: int, *, incarnation: int = 0,
                 token: int = 0, host: str = "127.0.0.1", port: int = 0,
                 codec: str = "zlib",
                 max_request_bytes: int = _DEFAULT_MAX_FRAME,
                 ckpt_dir: Optional[str] = None, save_every_s: float = 0.0,
                 base_every: int = 16, chaos=None, on_event=None):
        if codec not in _CODEC_IDS:
            raise ValueError(f"unknown replay service codec: {codec}")
        self.replay = replay
        self.shard_id = int(shard_id)
        self.incarnation = int(incarnation)
        self.token = int(token)
        self._codec_policy = codec
        self._accept_codecs = (
            {CODEC_OFF} if codec == "off" else {CODEC_OFF, CODEC_ZLIB}
        )
        self._max_frame = int(max_request_bytes)
        self._chaos = chaos
        self._on_event = on_event
        self._lsock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._lsock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._lsock.bind((host, int(port)))
        self._lsock.listen(128)
        self._lsock.setblocking(False)
        self.host = host
        self.port = self._lsock.getsockname()[1]
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._lock = threading.Lock()
        self._conns: Dict[int, _RConn] = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name=f"replay-shard{shard_id}", daemon=True
        )
        self._started = False
        # At-most-once adds: client_id -> (last applied req_id, its reply
        # payload).  A retried duplicate is answered from here WITHOUT
        # re-applying; req_ids are monotone per client by contract.
        self._last_add: Dict[int, Tuple[int, bytes]] = {}
        # Counters (the shard half of the replay_svc schema).
        self.accepted = 0
        self.requests = 0
        self.replies = 0
        self.errors = 0
        self.torn_frames = 0
        self.bad_hellos = 0
        self.stale_rejects = 0
        self.add_dups = 0
        self.ops = {name: 0 for name in _OP_NAMES.values()}
        self.chaos_dropped = 0
        self.chaos_delay_s = 0.0
        self.bytes_in = 0
        self.bytes_out = 0
        self.logical_bytes_in = 0   # decoded add/update record bytes
        # service_codec=auto control loop: compress sample replies only
        # while the reply path observes kernel-buffer backpressure
        # (blocked sends), so the incompressible worst case — zlib CPU
        # for bytes the link didn't need (the priced 16.8 ms leg in
        # demos/replay_svc.json) — is paid only when the wire is the
        # bottleneck.  The hello still negotiates the CAPABILITY; this
        # gates per-reply use.
        self.reply_full_waits = 0   # sends that hit a full kernel buffer
        self.reply_zlib = 0         # sample replies shipped compressed
        self.reply_raw = 0          # sample replies shipped raw
        # Per-request service latency (request verified → reply enqueued)
        # on the shared log-bucket layout, so the fleet aggregator can
        # merge shard histograms bucket-wise across the fleet; plus the
        # cross-tier span log (a traced request's server-side hop).
        self.op_ms = LatencyHistogram(min_s=1e-5, max_s=120.0)
        # Newest trace id per op-latency bucket (fleet-rollup
        # exemplars: a replay op p95 spike links to its timeline).
        self.op_exemplars = BucketExemplars(self.op_ms)
        self.spans = TraceSpanLog(depth=64)
        self._auto_on = False
        self._auto_idle = 0
        self._auto_fw_mark = 0
        # Shard-owned persistence: the incremental chain under
        # <ckpt_dir>; save() runs on the pump thread at the wall cadence
        # (step = transitions ever added — the shard's own clock).
        # Tiered (spill-backed) hosting: spans/bytes spilled cold by the
        # pump thread's watermark sweep (zeros on an untiered store).
        self.spill_spans = 0
        self.spill_bytes = 0
        self._ckpt = None
        self._save_every_s = float(save_every_s)
        self._next_save = time.monotonic() + self._save_every_s
        self.saves = 0
        if ckpt_dir:
            from ape_x_dqn_tpu.utils.checkpoint_inc import (
                IncrementalCheckpointer,
            )

            self._ckpt = IncrementalCheckpointer(
                ckpt_dir, replay, base_every=base_every
            )

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplayShardServer":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        self._wake()
        if self._started:
            self._thread.join(timeout=10.0)
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for c in conns:
            try:
                c.sock.close()
            except OSError:
                pass
        try:
            self._lsock.close()
        except OSError:
            pass
        self._wake_r.close()
        self._wake_w.close()
        if self._ckpt is not None:
            # Final committed snapshot so a clean stop never loses the
            # tail (a SIGKILL loses at most one save interval — the chain
            # is the recovery contract either way).
            try:
                self._ckpt.save(int(self.replay.total_added))
                self._ckpt.close(timeout=30.0)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    def __enter__(self) -> "ReplayShardServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _wake(self) -> None:
        try:
            self._wake_w.send(b"\x00")
        except OSError:
            pass

    def _event(self, kind: str, **fields) -> None:
        if self._on_event is not None:
            try:
                self._on_event(kind, shard=self.shard_id, **fields)
            except Exception:  # noqa: BLE001 — telemetry must not serve
                pass

    # -- pump thread -------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                socks = {c.sock: c for c in self._conns.values()}
                wlist = [c.sock for c in self._conns.values() if c.outbox]
            rlist = [self._lsock, self._wake_r, *socks]
            try:
                r, w, _ = select.select(rlist, wlist, [], 0.25)
            except (OSError, ValueError):
                time.sleep(0.005)
                continue
            if self._wake_r in r:
                try:
                    while self._wake_r.recv(4096):
                        pass
                except OSError:
                    pass
            if self._lsock in r:
                self._accept_pending()
            for sock in w:
                conn = socks.get(sock)
                if conn is not None:
                    self._flush(conn)
            for sock in r:
                conn = socks.get(sock)
                if conn is not None:
                    self._on_readable(conn)
            self._maybe_save()
            self._maybe_spill()

    def _maybe_spill(self) -> None:
        """Spill-backed shard (replay.service_hot_frame_budget_bytes):
        evict cold spans on the pump thread when the tiered store runs
        over its high watermark — serialized with every mutation by
        construction, so the spill never races an add.  A no-op on the
        untiered store."""
        over = getattr(self.replay, "tier_over_watermark", None)
        if over is None or not over():
            return
        try:
            spans, nbytes = self.replay.spill_cold()
            self.spill_spans += int(spans)
            self.spill_bytes += int(nbytes)
        except Exception as e:  # noqa: BLE001 — a sick spill path is an event, sampling stays correct
            self._event("shard_spill_error",
                        error=f"{type(e).__name__}: {e}")

    def _maybe_save(self) -> None:
        if self._ckpt is None or self._save_every_s <= 0:
            return
        now = time.monotonic()
        if now < self._next_save:
            return
        self._next_save = now + self._save_every_s
        try:
            if self._ckpt.save(int(self.replay.total_added)):
                self.saves += 1
        except Exception as e:  # noqa: BLE001 — a dead writer is an event
            self._event("shard_ckpt_error",
                        error=f"{type(e).__name__}: {e}")

    def _accept_pending(self) -> None:
        while True:
            try:
                sock, _addr = self._lsock.accept()
            except (BlockingIOError, InterruptedError, OSError):
                return
            sock.setblocking(False)
            try:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            self.accepted += 1
            with self._lock:
                self._conns[sock.fileno()] = _RConn(sock, self._max_frame)

    def _retire(self, conn: _RConn, torn: bool = False) -> None:
        if torn or conn.parser.pending() or conn.parser.error is not None:
            self.torn_frames += 1
        with self._lock:
            self._conns.pop(conn.sock.fileno(), None)
            self.bytes_in += conn.bytes_in
            self.bytes_out += conn.bytes_out
        try:
            conn.sock.close()
        except OSError:
            pass

    def _on_readable(self, conn: _RConn) -> None:
        while True:
            try:
                data = conn.sock.recv(_RECV_CHUNK)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._retire(conn)
                return
            if not data:
                self._retire(conn)
                return
            conn.bytes_in += len(data)
            if conn.client_id is None:
                need = RSVC_HELLO.size - len(conn.hello)
                conn.hello += data[:need]
                data = data[need:]
                if len(conn.hello) == RSVC_HELLO.size:
                    if not self._admit(conn):
                        return
                if not data:
                    continue
            conn.parser.feed(data)
        if conn.client_id is not None:
            self._drain_frames(conn)

    def _admit(self, conn: _RConn) -> bool:
        """Verify the hello; ack or reject-by-close.  A stale incarnation
        (the client pinning one this shard has outlived — or a client
        from before a respawn pinning the OLD incarnation against the new
        process) is rejected BEFORE any framing state exists."""
        try:
            (magic, version, client_id, shard_id, incarnation, token, codec,
             flags) = RSVC_HELLO.unpack(bytes(conn.hello))
        except struct.error:
            magic = b""
            version = client_id = shard_id = incarnation = token = -1
            codec, flags = 255, 0
        ok = (magic == RSVC_MAGIC and version == RSVC_VERSION
              and shard_id == self.shard_id and token == self.token)
        stale = ok and incarnation not in (-1, self.incarnation)
        if stale:
            self.stale_rejects += 1
        elif not ok:
            self.bad_hellos += 1
        if ok and not stale and codec not in self._accept_codecs:
            # Codec-mismatch hello: refused at the handshake, the
            # experience plane's codec_rejects rung.
            self.bad_hellos += 1
            ok = False
        if not ok or stale:
            self._retire(conn)
            return False
        conn.client_id = int(client_id)
        conn.codec = int(codec)
        conn.flags = int(flags)
        ack = RSVC_ACK.pack(
            RSVC_ACK_MAGIC, RSVC_VERSION, self.shard_id, self.incarnation,
            int(self.replay.capacity), int(self.replay.total_added),
        )
        conn.outbox.append(ack)   # raw bytes before the framed stream
        self._flush(conn)
        return True

    def _drain_frames(self, conn: _RConn) -> None:
        while True:
            got = conn.parser.next()
            if got is None:
                if conn.parser.error is not None:
                    self._retire(conn, torn=True)
                return
            kind, payload = got
            if kind != F_RREQ:
                # Reply kinds only flow shard → client: stream corruption,
                # connection-level recovery.
                self._retire(conn, torn=True)
                return
            self._handle(conn, payload)

    # -- request execution -------------------------------------------------

    def _handle(self, conn: _RConn, payload: bytes) -> None:
        t_req = time.monotonic()
        trace_id = 0
        if conn.flags & HELLO_FLAG_TRACE:
            # Trace-negotiated connection: every request leads with its
            # i64 trace id (0 = unsampled) — the version-gated envelope.
            try:
                trace_id, payload = split_trace(payload)
            except ValueError as e:
                self.errors += 1
                self._reply_err(conn, 0, RE_BAD_REQUEST, str(e))
                return
        if len(payload) < _RPC.size:
            self.errors += 1
            self._reply_err(conn, 0, RE_BAD_REQUEST, "short rpc head")
            return
        req_id, op = _RPC.unpack_from(payload, 0)
        body = memoryview(payload)[_RPC.size:]
        self.requests += 1
        if self._chaos is not None:
            d = self._chaos.delay_s()
            if d > 0:
                # Injected service latency: sleeping the pump thread IS
                # the fault (every queued request behind it waits too).
                self.chaos_delay_s += d
                time.sleep(d)
            if self._chaos.drop():
                # Silently dropped request: the lost-reply shape.  The
                # client's deadline expires and it retries whole.
                self.chaos_dropped += 1
                return
        try:
            if op == OP_ADD:
                self._op_add(conn, req_id, body)
            elif op == OP_SAMPLE:
                self._op_sample(conn, req_id, body)
            elif op == OP_UPDATE:
                self._op_update(conn, req_id, body)
            elif op == OP_DIGEST:
                self._op_digest(conn, req_id, body)
            elif op == OP_STATS:
                self.ops["stats"] += 1
                self._reply(conn, req_id, op,
                            json.dumps(self.stats()).encode())
            else:
                self.errors += 1
                self._reply_err(conn, req_id, RE_BAD_REQUEST,
                                f"unknown op {op}")
        except ValueError as e:
            # Well-framed but undecodable/ill-shaped body (the crc already
            # verified these bytes arrived intact): typed, not torn.
            self.errors += 1
            self._reply_err(conn, req_id, RE_BAD_REQUEST, str(e))
        except Exception as e:  # noqa: BLE001 — op raised: typed internal
            self.errors += 1
            self._reply_err(conn, req_id, RE_INTERNAL,
                            f"{type(e).__name__}: {e}")
        # Service latency (request verified → reply enqueued) always;
        # the cross-tier span only when the request carried a trace id.
        op_s = time.monotonic() - t_req
        self.op_ms.record(op_s)
        self.op_exemplars.record(op_s, trace_id)
        self.spans.record(trace_id, f"rsvc.{_OP_NAMES.get(op, str(op))}",
                          t_req, shard=self.shard_id, op=int(op))

    def _op_add(self, conn: _RConn, req_id: int, body) -> None:
        self.ops["add"] += 1
        last = self._last_add.get(conn.client_id)
        if last is not None and req_id <= last[0]:
            # Duplicate of an ALREADY-APPLIED add (the reply was lost):
            # at-most-once per req_id — answer from cache, never re-apply.
            self.add_dups += 1
            if req_id == last[0]:
                self._reply(conn, req_id, OP_ADD, last[1], flags=FLAG_DUP)
            else:
                self._reply_err(conn, req_id, RE_BAD_REQUEST,
                                "stale add req_id")
            return
        arrays = decode_body(body, allow_zlib=conn.codec != CODEC_OFF,
                             max_bytes=self._max_frame)
        self.logical_bytes_in += sum(a.nbytes for a in arrays.values())
        prio = np.asarray(arrays.pop("prio"), np.float64)
        idx = self.replay.add(prio, _Transition(arrays))
        rep = encode_body({"idx": np.asarray(idx, np.int64)},
                          codec=CODEC_OFF, dedup=False)
        self._last_add[conn.client_id] = (int(req_id), rep)
        self._reply(conn, req_id, OP_ADD, rep)

    def _op_sample(self, conn: _RConn, req_id: int, body) -> None:
        self.ops["sample"] += 1
        if len(body) < _SAMPLE_REQ.size:
            raise ValueError("short sample request")
        batch, _beta, seed = _SAMPLE_REQ.unpack_from(body, 0)
        if not 0 < batch <= 1 << 16:
            raise ValueError(f"absurd sample batch {batch}")
        if self.replay.size() == 0:
            self.errors += 1
            self._reply_err(conn, req_id, RE_EMPTY, "empty shard")
            return
        rng = np.random.default_rng(int(seed))
        transition, idx, mass, total, size = self.replay.sample_with_mass(
            int(batch), rng
        )
        rep_body = encode_body(
            {
                "obs": np.asarray(transition.obs),
                "action": np.asarray(transition.action),
                "reward": np.asarray(transition.reward),
                "discount": np.asarray(transition.discount),
                "next_obs": np.asarray(transition.next_obs),
                "idx": np.asarray(idx, np.int64),
                "mass": np.asarray(mass, np.float64),
            },
            codec=self._reply_codec()
            if conn.codec != CODEC_OFF else CODEC_OFF,
            dedup=True,
        )
        if rep_body[:1] == b"\x01":
            self.reply_zlib += 1
        else:
            self.reply_raw += 1
        self._reply(conn, req_id, OP_SAMPLE,
                    _SAMPLE_REP.pack(float(total), int(size)) + rep_body)

    def _op_update(self, conn: _RConn, req_id: int, body) -> None:
        self.ops["update"] += 1
        arrays = decode_body(body, allow_zlib=conn.codec != CODEC_OFF,
                             max_bytes=self._max_frame)
        self.logical_bytes_in += sum(a.nbytes for a in arrays.values())
        idx = np.asarray(arrays["idx"], np.int64)
        prio = np.asarray(arrays["prio"], np.float64)
        if idx.shape != prio.shape:
            raise ValueError("update idx/prio shape mismatch")
        if idx.size and (idx.min() < 0 or idx.max() >= self.replay.capacity):
            raise ValueError("update index outside the shard's slot range")
        self.replay.update_priorities(idx, prio)
        self._reply(conn, req_id, OP_UPDATE, b"")

    def _op_digest(self, conn: _RConn, req_id: int, body) -> None:
        self.ops["digest"] += 1
        with_crc = bool(len(body) >= _DIGEST_REQ.size
                        and _DIGEST_REQ.unpack_from(body, 0)[0])
        d = self.replay.digest(with_crc=with_crc)
        self._reply(conn, req_id, OP_DIGEST, _DIGEST_REP.pack(
            d["count"], d["cursor"], d["size"], self.incarnation,
            int(self.replay.capacity), d["total_mass"], d["crc"],
        ))

    # -- reply path --------------------------------------------------------

    def _reply_codec(self) -> int:
        """Effective SAMPLE-reply codec under the shard's policy.  "auto"
        mirrors NetWriter's control loop: zlib turns on when a reply send
        blocked since the last check (the wire is the bottleneck — codec
        CPU now buys throughput) and reverts after _AUTO_OFF_REPLIES
        backpressure-free replies (a fast link stops paying for bytes it
        doesn't need)."""
        if self._codec_policy == "zlib":
            return CODEC_ZLIB
        if self._codec_policy != "auto":
            return CODEC_OFF
        if self.reply_full_waits > self._auto_fw_mark:
            self._auto_fw_mark = self.reply_full_waits
            self._auto_on = True
            self._auto_idle = 0
        elif self._auto_on:
            self._auto_idle += 1
            if self._auto_idle >= _AUTO_OFF_REPLIES:
                self._auto_on = False
        return CODEC_ZLIB if self._auto_on else CODEC_OFF

    def _reply(self, conn: _RConn, req_id: int, op: int, body,
               flags: int = 0) -> None:
        self.replies += 1
        self._enqueue(conn, F_RREP, _RREP.pack(int(req_id), int(op),
                                               int(flags)) + bytes(body))

    def _reply_err(self, conn: _RConn, req_id: int, code: int,
                   message: str) -> None:
        self._enqueue(conn, F_RERR,
                      _RERR.pack(int(req_id), int(code))
                      + message.encode()[:512])

    def _enqueue(self, conn: _RConn, kind: int, body: bytes) -> None:
        with self._lock:
            if self._conns.get(conn.sock.fileno()) is not conn:
                return
            conn.out_seq += 1
            conn.outbox.append(frame_bytes(kind, conn.out_seq, [body]))
        self._flush(conn)

    def _flush(self, conn: _RConn) -> None:
        while True:
            with self._lock:
                if not conn.outbox:
                    return
                buf = conn.outbox[0]
            try:
                n = conn.sock.send(memoryview(buf)[conn.out_off:])
            except (BlockingIOError, InterruptedError):
                # Kernel send buffer full: the reply path is wire-bound —
                # the signal the auto codec gate compresses on.
                self.reply_full_waits += 1
                return
            except OSError:
                self._retire(conn)
                return
            conn.bytes_out += n
            conn.out_off += n
            if conn.out_off >= len(buf):
                conn.out_off = 0
                with self._lock:
                    if conn.outbox:
                        conn.outbox.popleft()

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            conns = [c for c in self._conns.values()
                     if c.client_id is not None]
            bytes_in = self.bytes_in + sum(
                c.bytes_in for c in self._conns.values()
            )
            bytes_out = self.bytes_out + sum(
                c.bytes_out for c in self._conns.values()
            )
        out = {
            "shard": self.shard_id,
            "incarnation": self.incarnation,
            "port": self.port,
            "connections": len(conns),
            "accepted": self.accepted,
            "requests": self.requests,
            "replies": self.replies,
            "errors": self.errors,
            "torn_frames": self.torn_frames,
            "bad_hellos": self.bad_hellos,
            "stale_rejects": self.stale_rejects,
            "add_dups": self.add_dups,
            "ops": dict(self.ops),
            "chaos_dropped": self.chaos_dropped,
            "bytes_in": bytes_in,
            "bytes_out": bytes_out,
            "logical_bytes_in": self.logical_bytes_in,
            "codec_policy": self._codec_policy,
            "reply_full_waits": self.reply_full_waits,
            "reply_zlib": self.reply_zlib,
            "reply_raw": self.reply_raw,
            "auto_codec_on": self._auto_on,
            "size": int(self.replay.size()),
            "capacity": int(self.replay.capacity),
            "total_added": int(self.replay.total_added),
            "saves": self.saves,
            "spill_spans": self.spill_spans,
            "spill_bytes": self.spill_bytes,
            # Fleet-rollup surfaces (obs/fleet.py): the service-latency
            # histogram ships summary + raw buckets so the aggregator can
            # merge shards bucket-wise; recent cross-tier spans ride the
            # same stats RPC (the shard's half of an end-to-end trace).
            "op_ms": {**self.op_ms.summary(),
                      "buckets": self.op_ms.buckets(),
                      "exemplars": self.op_exemplars.snapshot()},
            "trace_spans": self.spans.snapshot(),
        }
        if self._ckpt is not None:
            out["ckpt"] = self._ckpt.stats()
        return out


# ---------------------------------------------------------------------------
# Client side: one retrying shard client + the fleet-wide facade.
# ---------------------------------------------------------------------------


class ShardClient:
    """Blocking retrying RPC client against one shard — the ServingClient
    discipline on the replay plane: per-request deadline, jittered
    exponential reconnect backoff, WHOLE-request retry across reconnects
    (same req_id for the request's whole retry span — the shard's
    at-most-once add dedup keys on it), and a backoff that resets ONLY on
    a verified reply, so a dead shard is probed at backoff pace, never
    hammered.

    The endpoint (host/port/incarnation) is a mutable registry view the
    owner updates after a re-resolve; the hello pins the registry's
    incarnation when known, so a stale view is rejected at the handshake
    instead of talking to the wrong process generation.
    """

    def __init__(self, shard_id: int, host: str, port: int, *, token: int,
                 client_id: int, incarnation: int = -1, codec: str = "zlib",
                 trace: bool = False,
                 connect_timeout_s: float = 1.0, io_timeout_s: float = 5.0,
                 max_frame: int = _DEFAULT_MAX_FRAME, seed: int = 0,
                 on_incarnation: Optional[Callable[[int, int], None]] = None):
        if codec not in _CODEC_IDS:
            raise ValueError(f"unknown replay service codec: {codec}")
        self.shard_id = int(shard_id)
        self.host = host
        self.port = int(port)
        self.token = int(token)
        self.client_id = int(client_id)
        self.incarnation = int(incarnation)   # registry view; -1 = unknown
        self.codec = codec
        self._codec_id = _CODEC_IDS[codec]
        # Cross-tier tracing: negotiated at the hello (flags bit); with it
        # every request leads with an i64 trace id.  Off = the pre-flags
        # wire, byte for byte.
        self.trace = bool(trace)
        self._connect_timeout = float(connect_timeout_s)
        self._io_timeout = float(io_timeout_s)
        self._max_frame = int(max_frame)
        self._on_incarnation = on_incarnation
        self._sock: Optional[socket.socket] = None
        self._parser = FrameParser(max_frame=max_frame)
        self._backoff = Backoff(base_s=0.05, max_s=1.0,
                                seed=seed ^ (shard_id << 4))
        self._req_id = 0
        self._out_seq = 0
        self.capacity = 0             # learned from the ack
        self.reconnects = 0
        self.retries = 0
        self.torn = 0                 # parser faults / protocol violations
        self.hello_rejects = 0        # closed before the ack (stale/token)
        self._ever_connected = False

    # -- connection --------------------------------------------------------

    def set_endpoint(self, host: str, port: int, incarnation: int) -> None:
        """Adopt a re-resolved endpoint (the fleet moved the shard).  An
        open connection to the OLD endpoint is dropped."""
        if (host, int(port)) != (self.host, self.port) \
                or int(incarnation) != self.incarnation:
            self.host, self.port = host, int(port)
            self.incarnation = int(incarnation)
            self._drop()
            self._backoff.reset()

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _ensure_connected(self, deadline: float) -> bool:
        if self._sock is not None:
            return True
        if not self._backoff.ready():
            return False
        try:
            sock = socket.create_connection(
                (self.host, self.port), timeout=self._connect_timeout
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.sendall(RSVC_HELLO.pack(
                RSVC_MAGIC, RSVC_VERSION, self.client_id, self.shard_id,
                self.incarnation, self.token, self._codec_id,
                HELLO_FLAG_TRACE if self.trace else 0,
            ))
            sock.settimeout(
                max(0.05, min(self._io_timeout,
                              deadline - time.monotonic()))
            )
            ack = b""
            while len(ack) < RSVC_ACK.size:
                got = sock.recv(RSVC_ACK.size - len(ack))
                if not got:
                    raise OSError("closed before ack (stale/rejected hello)")
                ack += got
            magic, version, shard_id, incarnation, capacity, _count = \
                RSVC_ACK.unpack(ack)
            if magic != RSVC_ACK_MAGIC or version != RSVC_VERSION \
                    or shard_id != self.shard_id:
                raise OSError("bad ack")
        except (OSError, socket.timeout) as e:
            if "rejected" in str(e):
                self.hello_rejects += 1
            self._backoff.fail()
            return False
        self._sock = sock
        self._parser = FrameParser(max_frame=self._max_frame)
        self._out_seq = 0
        self.capacity = int(capacity)
        if incarnation != self.incarnation:
            self.incarnation = int(incarnation)
            if self._on_incarnation is not None:
                self._on_incarnation(self.shard_id, int(incarnation))
        # NB: the backoff resets on a verified REPLY, not here — an
        # accept-then-die shard must not turn the client into a tight
        # connect loop (the ServingClient discipline, pinned by tests).
        self.reconnects += int(self._ever_connected)
        self._ever_connected = True
        return True

    # -- request path ------------------------------------------------------

    def next_req_id(self) -> int:
        self._req_id += 1
        return self._req_id

    def request(self, op: int, body: bytes = b"",
                timeout: float = 10.0,
                req_id: Optional[int] = None,
                trace_id: int = 0) -> Tuple[int, bytes]:
        """(flags, reply payload past the head) for one RPC, across
        reconnects and whole-request retries.  Raises
        :class:`ReplayRpcError` on a typed refusal (the request WAS
        answered) and :class:`ReplayShardUnavailable` when the deadline
        expires unanswered.  ``trace_id`` rides the trace prefix on a
        trace-negotiated connection (retries re-send it unchanged — the
        whole retry span is one logical traced request)."""
        deadline = time.monotonic() + timeout
        rid = self.next_req_id() if req_id is None else int(req_id)
        payload = _RPC.pack(rid, int(op)) + body
        if self.trace:
            payload = wrap_trace(trace_id, payload)
        first = True
        while time.monotonic() < deadline:
            if not self._ensure_connected(deadline):
                time.sleep(0.005)
                continue
            if not first:
                self.retries += 1
            first = False
            try:
                self._out_seq += 1
                self._sock.sendall(
                    frame_bytes(F_RREQ, self._out_seq, [payload])
                )
                got = self._await(rid, deadline)
            except (OSError, socket.timeout):
                self._drop()
                self._backoff.fail()
                continue
            if got is None:          # torn stream / stale reply: retry
                continue
            kind, reply = got
            if kind == F_RREP:
                self._backoff.reset()
                _rid, _rop, flags = _RREP.unpack_from(reply, 0)
                return int(flags), bytes(reply[_RREP.size:])
            _rid, code = _RERR.unpack_from(reply, 0)
            msg = bytes(reply[_RERR.size:]).decode(errors="replace")
            if code == RE_CLOSED:
                # Shard draining: reconnect (the respawn will re-admit).
                self._drop()
                self._backoff.fail()
                continue
            self._backoff.reset()    # transport verified; typed refusal
            raise ReplayRpcError(int(code), msg)
        raise ReplayShardUnavailable(
            f"shard {self.shard_id} ({self.host}:{self.port}) gave no "
            f"reply within {timeout:.1f}s (retries={self.retries}, "
            f"reconnects={self.reconnects})",
            shard_id=self.shard_id, op=_OP_NAMES.get(op, str(op)),
        )

    def _await(self, rid: int, deadline: float):
        while True:
            got = self._parser.next()
            if got is not None:
                kind, payload = got
                if kind == F_RREP:
                    if len(payload) >= _RREP.size \
                            and _RREP.unpack_from(payload, 0)[0] == rid:
                        return kind, payload
                    continue          # stale reply from a retried request
                if kind == F_RERR:
                    if len(payload) >= _RERR.size \
                            and _RERR.unpack_from(payload, 0)[0] in (rid, 0):
                        return kind, payload
                    continue
                # Unknown kind: protocol violation — torn.
                self.torn += 1
                self._drop()
                self._backoff.fail()
                return None
            if self._parser.error is not None:
                self.torn += 1
                self._drop()
                self._backoff.fail()
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("deadline")
            self._sock.settimeout(min(self._io_timeout, remaining))
            data = self._sock.recv(_RECV_CHUNK)
            if not data:
                raise OSError("connection closed by peer")
            self._parser.feed(data)

    # -- typed ops ---------------------------------------------------------

    def digest(self, with_crc: bool = False, timeout: float = 2.0) -> dict:
        _flags, body = self.request(
            OP_DIGEST, _DIGEST_REQ.pack(int(with_crc)), timeout=timeout
        )
        count, cursor, size, incarnation, capacity, total_mass, crc = \
            _DIGEST_REP.unpack_from(body, 0)
        return {"count": count, "cursor": cursor, "size": size,
                "incarnation": incarnation, "capacity": capacity,
                "total_mass": total_mass, "crc": crc}

    def shard_stats(self, timeout: float = 2.0) -> dict:
        _flags, body = self.request(OP_STATS, timeout=timeout)
        return json.loads(body.decode())

    def close(self) -> None:
        self._drop()


def _membership_shards(snapshot: dict) -> List[dict]:
    """Endpoint-file-shaped shard dicts from a fleet-registry snapshot:
    the ``replay_shard`` members with live ports, sid recovered from the
    slot-range base (``base // capacity`` — the fleet keeps shards
    uniform and contiguous, so the mapping is exact)."""
    out = []
    for m in snapshot.get("members", {}).values():
        if m.get("kind") != "replay_shard":
            continue
        port = int(m.get("port", 0))
        cap = int(m.get("capacity", 0))
        if port <= 0 or cap <= 0:
            continue
        out.append({
            "id": int(m.get("base", 0)) // cap,
            "host": str(m.get("host", "127.0.0.1")),
            "port": port,
            "base": int(m.get("base", 0)),
            "capacity": cap,
            "incarnation": int(m.get("incarnation", -1)),
            "draining": bool(m.get("draining", False)),
        })
    return sorted(out, key=lambda s: s["id"])


class ShardedReplayClient:
    """The learner-facing replay: a PrioritizedReplay-shaped facade
    (``add`` / ``sample`` / ``update_priorities`` / ``size``) over the
    shard fleet, fault-tolerant by construction.

    Degradation contract — a shard dying costs the learner THROUGHPUT,
    never correctness and never a wedge:

      * ``sample`` draws the whole batch from one shard chosen by p^α
        mass among the HEALTHY shards (mass-weighted shard choice ×
        in-shard proportional sampling = the global sampling law, modulo
        the staleness of cached shard totals — the same order the async
        Ape-X loop already tolerates); IS weights are normalized against
        the GLOBAL (all-shard) total and size.
      * ``add`` routes round-robin over healthy shards; a shard going
        down mid-add re-routes to a survivor (at-least-once across the
        fleet; at-most-once per shard via the req_id dedup).
      * ``update_priorities`` routes by slot range; write-backs to a
        down shard buffer LAST-WRITE-WINS client-side and flush as one
        batched update when the background probe sees the shard return.
      * Only when EVERY shard is unreachable does an op raise the typed
        :class:`ReplayShardUnavailable`; ``age_s`` (the ``replay_svc``
        health component) reports how long the fleet has been degraded.

    The routing set is ELASTIC: shard clients live in sid-keyed maps, so
    :meth:`adopt_membership` (fed by the fleet registry's snapshots —
    :meth:`from_registry`) can admit a grown shard, stop routing adds at
    a draining one, and retire a removed one without rebuilding the
    facade.  Priority write-backs routed at a since-retired slot range
    are counted (``updates_dropped``), never raised — the transitions
    themselves were handed off server-side.
    """

    remote = True

    def __init__(self, shards: Sequence[dict], *, token: int,
                 codec: str = "zlib", dedup: bool = True,
                 trace: bool = False,
                 request_timeout_s: float = 10.0,
                 probe_interval_s: float = 0.5,
                 client_id: Optional[int] = None,
                 endpoints_path: Optional[str] = None,
                 seed: int = 0, on_event=None):
        shards = sorted(shards, key=lambda s: int(s["id"]))
        if not shards:
            raise ValueError("replay service needs >= 1 shard")
        caps = {int(s["capacity"]) for s in shards}
        if len(caps) != 1:
            raise ValueError("shards must have uniform capacity "
                             f"(got {sorted(caps)})")
        self.shard_capacity = caps.pop()
        self.num_shards = len(shards)
        self.capacity = self.shard_capacity * self.num_shards
        for k, s in enumerate(shards):
            if int(s["id"]) != k or int(s["base"]) != k * self.shard_capacity:
                raise ValueError("shard ids/bases must tile [0, capacity)")
        self._dedup = bool(dedup)
        self._token = int(token)
        self._codec_name = codec
        self._codec_id = _CODEC_IDS[codec]
        self._timeout = float(request_timeout_s)
        self._probe_interval = float(probe_interval_s)
        self._endpoints_path = endpoints_path
        self._endpoints_digest: Optional[int] = None
        self._seed = int(seed)
        self._on_event = on_event
        if client_id is None:
            client_id = (os.getpid() << 16) ^ secrets.randbits(16)
        self.client_id = int(client_id)
        # Elastic routing set: sid-keyed, mutated only under _state by
        # adopt_membership; readers take point-in-time copies.
        self._clients: Dict[int, ShardClient] = {}
        self._locks: Dict[int, threading.Lock] = {}
        # Cross-tier tracing (negotiated per connection): the learner's
        # RPC hops join the experience lineage — client-side spans land
        # here, the shard-side halves ride each shard's stats RPC.
        self.trace = bool(trace)
        self.spans = TraceSpanLog(depth=128)
        self._last_sample: Optional[Tuple[int, float, float]] = None
        for s in shards:
            sid = int(s["id"])
            self._clients[sid] = self._make_shard_client(
                sid, s["host"], int(s["port"]),
                int(s.get("incarnation", -1)),
            )
            self._locks[sid] = threading.Lock()
        self._state = threading.Lock()
        self._down: Dict[int, float] = {}        # sid -> down_since
        self._draining: set = set()              # sids leaving the add path
        self._pending: Dict[int, Dict[int, float]] = {}  # sid -> idx->prio
        self._totals: Dict[int, float] = {       # cached p^α mass per shard
            sid: 0.0 for sid in self._clients
        }
        self._sizes: Dict[int, int] = {sid: 0 for sid in self._clients}
        self._size_t = 0.0
        self._add_rr = 0
        self._degraded_since: Optional[float] = None
        # Counters (the client half of docs/METRICS.md "Replay service
        # schema" — key set pinned by tests/test_replay_svc.py).
        self.samples = 0
        self.adds = 0
        self.updates = 0
        self.add_rerouted = 0
        self.sample_rerouted = 0
        self.shard_unavailable = 0     # per-shard deadline expiries seen
        self.writeback_buffered = 0    # slots ever parked for a down shard
        self.writeback_flushed = 0     # slots flushed on recovery
        self.updates_dropped = 0       # slots routed at a retired shard
        self.probes = 0
        self.recoveries = 0
        self.membership_adopts = 0
        self.membership_version = -1
        # rpc_* accumulators of since-retired shard clients, so the
        # stats sums stay monotone across membership churn.
        self._retired_rpc = {"retries": 0, "reconnects": 0, "torn": 0,
                             "hello_rejects": 0}
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._watcher: Optional[FleetAnnouncer] = None

    def _make_shard_client(self, sid: int, host: str, port: int,
                           incarnation: int) -> ShardClient:
        return ShardClient(
            sid, host, int(port), token=self._token,
            client_id=self.client_id, incarnation=int(incarnation),
            codec=self._codec_name, trace=self.trace,
            io_timeout_s=min(5.0, self._timeout),
            seed=self._seed ^ self.client_id,
        )

    @classmethod
    def from_endpoints_file(cls, path: str, **kwargs) -> "ShardedReplayClient":
        with open(path) as f:
            doc = json.load(f)
        kwargs.setdefault("codec", doc.get("codec", "zlib"))
        return cls(doc["shards"], token=int(doc["token"]),
                   endpoints_path=path, **kwargs)

    @classmethod
    def from_registry(cls, host: str, port: int, *, token: int,
                      wait_timeout_s: float = 30.0,
                      **kwargs) -> "ShardedReplayClient":
        """Build a client whose routing set is DRIVEN by the fleet
        registry (``fleet.discovery=registry`` — no endpoints file):
        blocks until at least one ``replay_shard`` member is announced,
        then keeps adopting membership snapshots over a watcher
        heartbeat, so grow/drain/retire propagate without any file
        polling."""
        probe = FleetClient(
            host, int(port), token=int(token),
            member_id=member_id_for(f"replay-client-{os.getpid()}"),
        )
        deadline = time.monotonic() + float(wait_timeout_s)
        shards: List[dict] = []
        try:
            while time.monotonic() < deadline:
                try:
                    snap = probe.sync()
                except ConnectionError:
                    time.sleep(0.05)
                    continue
                shards = _membership_shards(snap)
                if shards:
                    break
                time.sleep(0.05)
        finally:
            probe.close()
        if not shards:
            raise ReplayShardUnavailable(
                f"no replay_shard member announced within "
                f"{wait_timeout_s:.1f}s", op="discover",
            )
        client = cls(shards, token=int(token), **kwargs)
        client._watch_registry(host, int(port))
        return client

    def _watch_registry(self, host: str, port: int) -> None:
        self._watcher = FleetAnnouncer(
            host, int(port), token=self._token,
            member_id=member_id_for(f"replay-client-{self.client_id}"),
            heartbeat_s=self._probe_interval,
            on_membership=self.adopt_membership,
            seed=self._seed ^ self.client_id,
        )
        self._watcher.start()

    # -- membership (the fleet registry's routing feed) --------------------

    def adopt_membership(self, snapshot: dict) -> None:
        """Adopt one registry snapshot as the routing set: new
        ``replay_shard`` members get clients, moved ones re-resolve,
        draining ones leave the add path, removed ones retire (their
        parked write-backs are DROPPED and counted — the slot range no
        longer exists).  An empty shard list never wipes the routing set
        (a registry cold start must not strand the learner)."""
        shards = _membership_shards(snapshot)
        specs = {int(s["id"]): s for s in shards
                 if int(s["capacity"]) == self.shard_capacity}
        if not specs:
            return
        removed: List[ShardClient] = []
        moved: List[Tuple[ShardClient, dict]] = []
        with self._state:
            current = set(self._clients)
            want = set(specs)
            for sid in sorted(want - current):
                m = specs[sid]
                self._clients[sid] = self._make_shard_client(
                    sid, m["host"], int(m["port"]),
                    int(m.get("incarnation", -1)),
                )
                self._locks[sid] = threading.Lock()
                self._totals.setdefault(sid, 0.0)
                self._sizes.setdefault(sid, 0)
            for sid in sorted(current - want):
                removed.append(self._clients.pop(sid))
                self._locks.pop(sid, None)
                self._totals.pop(sid, None)
                self._sizes.pop(sid, None)
                self._down.pop(sid, None)
                dropped = self._pending.pop(sid, None)
                if dropped:
                    self.updates_dropped += len(dropped)
            for sid in sorted(want & current):
                moved.append((self._clients[sid], specs[sid]))
            self._draining = {sid for sid, m in specs.items()
                              if m.get("draining")}
            self.num_shards = len(self._clients)
            self.capacity = self.shard_capacity * self.num_shards
            if not self._down:
                self._degraded_since = None
            for c in removed:
                self._retired_rpc["retries"] += c.retries
                self._retired_rpc["reconnects"] += c.reconnects
                self._retired_rpc["torn"] += c.torn
                self._retired_rpc["hello_rejects"] += c.hello_rejects
            self.membership_version = int(snapshot.get("version", -1))
            self.membership_adopts += 1
        for cli, m in moved:
            cli.set_endpoint(m["host"], int(m["port"]),
                             int(m.get("incarnation", -1)))
        for c in removed:
            c.close()
        if removed or (want - current):
            self._event("replay_routing_changed",
                        shards=sorted(specs),
                        version=self.membership_version)

    # -- health ------------------------------------------------------------

    def _healthy(self) -> List[int]:
        with self._state:
            return [k for k in sorted(self._clients) if k not in self._down]

    def _addable(self) -> List[int]:
        """Shards eligible for NEW experience: healthy and not draining
        (a draining shard still answers sample/update — its range is
        mid-handoff — but must stop accumulating)."""
        with self._state:
            return [k for k in sorted(self._clients)
                    if k not in self._down and k not in self._draining]

    @property
    def degraded(self) -> bool:
        with self._state:
            return bool(self._down)

    def age_s(self) -> float:
        """The ``replay_svc`` /healthz component: 0 while every shard
        answers; otherwise seconds since the fleet degraded."""
        with self._state:
            if not self._down:
                return 0.0
            return time.monotonic() - min(self._down.values())

    def _mark_down(self, sid: int, reason: str) -> None:
        start_probe = False
        with self._state:
            if sid not in self._down:
                self._down[sid] = time.monotonic()
                if self._degraded_since is None:
                    self._degraded_since = self._down[sid]
                start_probe = True
        self.shard_unavailable += 1
        if start_probe:
            self._event("replay_shard_down", shard=sid, reason=reason)
            self._ensure_probe_thread()

    def _mark_up(self, sid: int) -> None:
        with self._state:
            self._down.pop(sid, None)
            if not self._down:
                self._degraded_since = None
        self.recoveries += 1
        self._event("replay_shard_recovered_client", shard=sid)

    def _event(self, kind: str, **fields) -> None:
        if self._on_event is not None:
            try:
                self._on_event(kind, **fields)
            except Exception:  # noqa: BLE001 — observer callback must never break the fleet/client
                pass

    # -- the probe/recovery loop -------------------------------------------

    def _ensure_probe_thread(self) -> None:
        if self._probe_thread is None or not self._probe_thread.is_alive():
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="replay-svc-probe", daemon=True
            )
            self._probe_thread.start()

    def _refresh_endpoints(self) -> None:
        path = self._endpoints_path
        if not path:
            return
        try:
            # Change detection by CONTENT digest, never mtime equality:
            # two atomic rewrites can land inside one filesystem
            # timestamp granule, and an mtime early-out would skip the
            # second forever — the respawned shard's new port unseen,
            # the probe loop stuck dialing the old incarnation.
            with open(path, "rb") as f:
                raw = f.read()
            digest = zlib.crc32(raw)
            if digest == self._endpoints_digest:
                return
            doc = json.loads(raw.decode("utf-8"))
            self._endpoints_digest = digest
        except (OSError, ValueError):
            return
        for s in doc.get("shards", []):
            cli = self._clients.get(int(s["id"]))
            if cli is not None:
                cli.set_endpoint(
                    s["host"], int(s["port"]), int(s.get("incarnation", -1))
                )

    def _probe_loop(self) -> None:
        while not self._stop.wait(self._probe_interval):
            with self._state:
                down = list(self._down)
            if not down:
                continue
            self._refresh_endpoints()
            for sid in down:
                lock, cli = self._locks.get(sid), self._clients.get(sid)
                if lock is None or cli is None:
                    continue          # retired while parked on the down list
                self.probes += 1
                try:
                    with lock:
                        cli.digest(
                            with_crc=False,
                            timeout=max(0.25, self._probe_interval),
                        )
                        # Reachable again: flush the parked write-backs
                        # BEFORE re-admitting it to the routing set, so a
                        # sampler never races ahead of its own priorities.
                        self._flush_pending_locked(sid)
                except (ReplayShardUnavailable, ReplayRpcError):
                    continue
                self._mark_up(sid)

    def _flush_pending_locked(self, sid: int) -> None:
        """One batched last-write-wins update of everything parked for
        ``sid`` (caller holds the shard lock)."""
        with self._state:
            pending = self._pending.pop(sid, None)
        if not pending:
            return
        cli = self._clients.get(sid)
        if cli is None:
            # Retired mid-park: the slot range was handed off — the
            # priorities have nowhere valid to land.
            self.updates_dropped += len(pending)
            return
        idx = np.fromiter(pending.keys(), np.int64, len(pending))
        prio = np.fromiter(pending.values(), np.float64, len(pending))
        try:
            cli.request(
                OP_UPDATE,
                encode_body({"idx": idx, "prio": prio},
                            codec=self._codec_id, dedup=False),
                timeout=self._timeout,
            )
            self.writeback_flushed += len(pending)
            self._event("replay_writeback_flushed", shard=sid,
                        slots=len(pending))
        except (ReplayShardUnavailable, ReplayRpcError):
            # Still (or newly) unreachable: park them again — later
            # updates still win (dict.update order).
            with self._state:
                merged = self._pending.setdefault(sid, {})
                for k, v in pending.items():
                    merged.setdefault(k, v)
            raise ReplayShardUnavailable(
                f"shard {sid} reappeared but the write-back flush failed",
                shard_id=sid, op="update",
            )

    # -- replay surface ----------------------------------------------------

    def add(self, priorities: np.ndarray, batch,
            trace_id: int = 0) -> np.ndarray:
        """Route one chunk to a healthy shard; returns GLOBAL slot
        indices.  Re-routes to a survivor when the chosen shard dies
        mid-request.  ``trace_id`` (a traced chunk's lineage id) rides
        the RPC's trace prefix and stamps the client-side hop span."""
        arrays = {
            "prio": np.asarray(priorities, np.float64),
            "obs": np.asarray(batch.obs),
            "action": np.asarray(batch.action),
            "reward": np.asarray(batch.reward),
            "discount": np.asarray(batch.discount),
            "next_obs": np.asarray(batch.next_obs),
        }
        trace_id = trace_id if self.trace else 0
        body = encode_body(arrays, codec=self._codec_id, dedup=self._dedup)
        candidates = (self._addable() or self._healthy()
                      or sorted(self._clients))
        self._add_rr += 1
        order = candidates[self._add_rr % len(candidates):] \
            + candidates[:self._add_rr % len(candidates)]
        last_err: Optional[ReplayShardUnavailable] = None
        for pos, sid in enumerate(order):
            lock, cli = self._locks.get(sid), self._clients.get(sid)
            if lock is None or cli is None:
                continue              # retired between choice and dispatch
            try:
                t0 = time.monotonic()
                with lock:
                    _flags, rep = cli.request(
                        OP_ADD, body, timeout=self._timeout,
                        trace_id=trace_id,
                    )
                self.spans.record(trace_id, "rsvc.add.client", t0, shard=sid)
                idx = decode_body(rep)["idx"]
                self.adds += 1
                if pos:
                    self.add_rerouted += 1
                with self._state:
                    if sid in self._sizes:
                        self._sizes[sid] = min(
                            self._sizes[sid] + len(idx), self.shard_capacity
                        )
                return np.asarray(idx, np.int64) \
                    + sid * self.shard_capacity
            except ReplayShardUnavailable as e:
                last_err = e
                self._mark_down(sid, f"add: {e}")
        raise last_err if last_err is not None else ReplayShardUnavailable(
            "no healthy replay shard", op="add"
        )

    def sample(self, batch_size: int, beta: float = 0.4,
               rng: Optional[np.random.Generator] = None):
        """PrioritizedBatch with GLOBAL indices and globally-normalized
        IS weights — the drop-in for PrioritizedReplay.sample."""
        from ape_x_dqn_tpu.types import NStepTransition, PrioritizedBatch

        rng = rng or np.random.default_rng()
        candidates = self._healthy()
        if not candidates:
            candidates = sorted(self._clients)
        with self._state:
            totals = {k: max(0.0, self._totals.get(k, 0.0))
                      for k in candidates}
        # Mass-weighted shard order: positive-mass shards first (drawn
        # without replacement ∝ their cached p^α totals — shard choice ×
        # in-shard proportional = the global law), zero/unknown-mass
        # shards shuffled behind them as fallbacks.
        pos = [k for k in candidates if totals[k] > 0]
        zero = [k for k in candidates if totals[k] <= 0]
        order: List[int] = []
        if pos:
            p = np.asarray([totals[k] for k in pos])
            order += list(rng.choice(pos, size=len(pos), replace=False,
                                     p=p / p.sum()))
        rng.shuffle(zero)
        order += zero
        last_err: Optional[BaseException] = None
        for pos, sid in enumerate(map(int, order)):
            seed = int(rng.integers(0, 2 ** 63 - 1))
            lock, cli = self._locks.get(sid), self._clients.get(sid)
            if lock is None or cli is None:
                continue              # retired between choice and dispatch
            try:
                t0 = time.monotonic()
                with lock:
                    _flags, rep = cli.request(
                        OP_SAMPLE,
                        _SAMPLE_REQ.pack(int(batch_size), float(beta), seed),
                        timeout=self._timeout,
                    )
                # Whether this sample touched a traced experience is only
                # knowable AFTER lineage sees the slot indices — park the
                # hop and let tag_sample_span stamp it post-hoc.
                self._last_sample = (sid, t0, time.monotonic())
            except ReplayShardUnavailable as e:
                last_err = e
                self._mark_down(sid, f"sample: {e}")
                continue
            except ReplayRpcError as e:
                if e.code == RE_EMPTY:       # fresh shard: try another
                    last_err = e
                    continue
                raise
            if pos:
                self.sample_rerouted += 1
            total, size = _SAMPLE_REP.unpack_from(rep, 0)
            arrays = decode_body(rep[_SAMPLE_REP.size:])
            with self._state:
                if sid in self._clients:
                    self._totals[sid] = float(total)
                    self._sizes[sid] = int(size)
                g_total = sum(self._totals.values())
                g_size = sum(self._sizes.values())
            self.samples += 1
            mass = np.asarray(arrays["mass"], np.float64)
            probs = mass / max(g_total, 1e-12)
            w = np.power(
                max(g_size, 1) * np.maximum(probs, 1e-12), -float(beta)
            )
            return PrioritizedBatch(
                transition=NStepTransition(
                    obs=arrays["obs"], action=arrays["action"],
                    reward=arrays["reward"], discount=arrays["discount"],
                    next_obs=arrays["next_obs"],
                ),
                indices=(np.asarray(arrays["idx"], np.int64)
                         + sid * self.shard_capacity).astype(np.int32),
                is_weights=(w / w.max()).astype(np.float32),
            )
        if isinstance(last_err, ReplayRpcError):
            raise ValueError("cannot sample from an empty replay service")
        raise last_err if last_err is not None else ReplayShardUnavailable(
            "no healthy replay shard", op="sample"
        )

    def tag_sample_span(self, trace_id: int) -> None:
        """Stamp the newest sample RPC's client hop with a trace id (the
        learner calls this after lineage identifies a traced slot in the
        returned batch) — closing the sample leg of the e2e timeline."""
        parked, self._last_sample = self._last_sample, None
        if parked is not None and self.trace:
            sid, t0, t1 = parked
            self.spans.record(trace_id, "rsvc.sample.client", t0, t1,
                              shard=sid)

    def update_priorities(self, indices: np.ndarray,
                          priorities: np.ndarray,
                          trace_id: int = 0) -> None:
        """Split by slot range; a down shard's slice buffers
        last-write-wins and flushes on recovery — the learner never
        blocks on a dead shard's priorities.  ``trace_id`` marks the
        write-back of a traced experience (the timeline's final RPC
        hop)."""
        trace_id = trace_id if self.trace else 0
        indices = np.asarray(indices, np.int64)
        priorities = np.asarray(priorities, np.float64)
        if indices.size == 0:
            return
        sids = indices // self.shard_capacity
        for sid in map(int, np.unique(sids)):
            m = sids == sid
            idx = indices[m] - sid * self.shard_capacity
            prio = priorities[m]
            lock, cli = self._locks.get(sid), self._clients.get(sid)
            if lock is None or cli is None:
                # The slot range was retired (resharded away): the
                # transitions live on under NEW global indices on the
                # survivors — this stale write-back has no target.
                self.updates_dropped += int(idx.size)
                continue
            with self._state:
                down = sid in self._down
            if down:
                self._buffer_writeback(sid, idx, prio)
                continue
            try:
                t0 = time.monotonic()
                with lock:
                    cli.request(
                        OP_UPDATE,
                        encode_body({"idx": idx, "prio": prio},
                                    codec=self._codec_id, dedup=False),
                        timeout=self._timeout,
                        trace_id=trace_id,
                    )
                self.spans.record(trace_id, "rsvc.update.client", t0,
                                  shard=sid)
                self.updates += 1
            except ReplayShardUnavailable as e:
                self._buffer_writeback(sid, idx, prio)
                self._mark_down(sid, f"update: {e}")

    def _buffer_writeback(self, sid: int, idx: np.ndarray,
                          prio: np.ndarray) -> None:
        with self._state:
            d = self._pending.setdefault(sid, {})
            before = len(d)
            d.update(zip(idx.tolist(), prio.tolist()))
            self.writeback_buffered += len(idx)
            # Bound the parked set: it can never exceed the shard's slot
            # count (last-write-wins keys on the slot), so no cap needed —
            # but account growth for the stats surface.
            del before

    # -- size/meta ---------------------------------------------------------

    def size(self) -> int:
        now = time.monotonic()
        with self._state:
            stale = now - self._size_t > 0.25
            if stale:
                self._size_t = now
        if stale:
            for sid in self._healthy():
                lock, cli = self._locks.get(sid), self._clients.get(sid)
                if lock is None or cli is None:
                    continue
                try:
                    with lock:
                        d = cli.digest(
                            with_crc=False, timeout=min(2.0, self._timeout)
                        )
                    with self._state:
                        if sid in self._clients:
                            self._sizes[sid] = int(d["size"])
                            self._totals[sid] = float(d["total_mass"])
                except (ReplayShardUnavailable, ReplayRpcError) as e:
                    self._mark_down(sid, f"digest: {e}")
        with self._state:
            return int(sum(self._sizes.values()))

    @property
    def total_added(self) -> int:
        return self.adds

    def frames_nbytes(self) -> int:
        return 0   # remote: the shards own the bytes

    def max_priority(self) -> float:
        return 1.0

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        """The ``replay_svc`` JSONL / /varz section (docs/METRICS.md
        "Replay service schema" — key set pinned by
        tests/test_replay_svc.py)."""
        with self._state:
            down = sorted(self._down)
            draining = sorted(self._draining)
            pending = sum(len(d) for d in self._pending.values())
            sizes = list(self._sizes.values())
            totals = list(self._totals.values())
            clients = list(self._clients.values())
            retired = dict(self._retired_rpc)
        return {
            "shards": self.num_shards,
            "shards_down": len(down),
            "down": down,
            "shards_draining": draining,
            "degraded": bool(down),
            "degraded_age_s": round(self.age_s(), 3),
            "size": int(sum(sizes)),
            "total_mass": round(float(sum(totals)), 3),
            "samples": self.samples,
            "adds": self.adds,
            "updates": self.updates,
            "add_rerouted": self.add_rerouted,
            "sample_rerouted": self.sample_rerouted,
            "shard_unavailable": self.shard_unavailable,
            "writeback_buffered": self.writeback_buffered,
            "writeback_flushed": self.writeback_flushed,
            "writeback_pending": pending,
            "updates_dropped": self.updates_dropped,
            "probes": self.probes,
            "recoveries": self.recoveries,
            "membership_version": self.membership_version,
            "membership_adopts": self.membership_adopts,
            "rpc_retries": retired["retries"]
            + sum(c.retries for c in clients),
            "rpc_reconnects": retired["reconnects"]
            + sum(c.reconnects for c in clients),
            "rpc_torn": retired["torn"] + sum(c.torn for c in clients),
            "hello_rejects": retired["hello_rejects"]
            + sum(c.hello_rejects for c in clients),
        }

    def close(self) -> None:
        self._stop.set()
        if self._watcher is not None:
            self._watcher.close(leave=False)
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5.0)
        with self._state:
            pairs = [(self._locks[sid], self._clients[sid])
                     for sid in sorted(self._clients)]
        for lock, c in pairs:
            with lock:
                c.close()


# ---------------------------------------------------------------------------
# Fleet: shard subprocesses + supervision + the endpoints file.
# ---------------------------------------------------------------------------


class ReplayShardProcess:
    """One shard subprocess: ``python -m ape_x_dqn_tpu.replay.service``
    with its announce line parsed off stdout (the ReplicaProcess
    discipline — ephemeral ports are fine because the fleet republishes
    the endpoints file on every spawn)."""

    def __init__(self, shard_id: int, capacity: int, obs_shape, *,
                 token: int, root_dir: str, priority_exponent: float = 0.6,
                 codec: str = "zlib", save_every_s: float = 2.0,
                 base_every: int = 16, host: str = "127.0.0.1",
                 hot_frame_budget_bytes: int = 0,
                 rpc_delay_ms: float = 0.0, rpc_drop_rate: float = 0.0,
                 chaos_seed: int = 0):
        self.shard_id = int(shard_id)
        self.capacity = int(capacity)
        self.obs_shape = tuple(int(d) for d in obs_shape)
        self.token = int(token)
        self.hot_frame_budget_bytes = int(hot_frame_budget_bytes)
        # Absolute by contract: the shard subprocess runs with the REPO
        # as its cwd (for the -m import), so a relative dir would land
        # its chain inside the source tree.
        self.root_dir = os.path.abspath(root_dir)
        self.alpha = float(priority_exponent)
        self.codec = codec
        self.save_every_s = float(save_every_s)
        self.base_every = int(base_every)
        self.host = host
        self.rpc_delay_ms = float(rpc_delay_ms)
        self.rpc_drop_rate = float(rpc_drop_rate)
        self.chaos_seed = int(chaos_seed)
        self.incarnation = -1
        self.port: Optional[int] = None
        self.pid: Optional[int] = None
        self.proc: Optional[subprocess.Popen] = None
        self.events: List[dict] = []
        self._announce = threading.Event()
        self._reader: Optional[threading.Thread] = None

    @property
    def ckpt_dir(self) -> str:
        return os.path.join(self.root_dir, f"shard{self.shard_id}")

    def spawn(self) -> "ReplayShardProcess":
        self.incarnation += 1
        self.port = None
        self._announce.clear()
        os.makedirs(self.ckpt_dir, exist_ok=True)
        args = [
            sys.executable, "-m", "ape_x_dqn_tpu.replay.service",
            "--shard-id", str(self.shard_id),
            "--capacity", str(self.capacity),
            "--obs-shape", ",".join(map(str, self.obs_shape)),
            "--alpha", str(self.alpha),
            "--token", str(self.token),
            "--incarnation", str(self.incarnation),
            "--host", self.host, "--port", "0",
            "--codec", self.codec,
            "--ckpt-dir", self.ckpt_dir,
            "--save-every-s", str(self.save_every_s),
            "--base-every", str(self.base_every),
        ]
        if self.hot_frame_budget_bytes > 0:
            args += ["--hot-frame-budget-bytes",
                     str(self.hot_frame_budget_bytes)]
        if self.rpc_delay_ms or self.rpc_drop_rate:
            args += ["--rpc-delay-ms", str(self.rpc_delay_ms),
                     "--rpc-drop-rate", str(self.rpc_drop_rate),
                     "--chaos-seed", str(self.chaos_seed)]
        stderr_log = open(   # noqa: SIM115 — lives as long as the child
            os.path.join(self.ckpt_dir,
                         f"shard{self.shard_id}.{self.incarnation}.log"),
            "ab",
        )
        self.proc = subprocess.Popen(
            args, stdout=subprocess.PIPE, stderr=stderr_log,
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
        )
        stderr_log.close()
        self.pid = self.proc.pid
        self._reader = threading.Thread(
            target=self._read_stdout, args=(self.proc,),
            name=f"shard{self.shard_id}-stdout", daemon=True,
        )
        self._reader.start()
        return self

    def _read_stdout(self, proc: subprocess.Popen) -> None:
        for raw in iter(proc.stdout.readline, b""):
            try:
                ev = json.loads(raw.decode(errors="replace"))
            except ValueError:
                continue
            self.events.append(ev)
            if len(self.events) > 512:
                del self.events[:128]
            if ev.get("event") == "replay_shard_listen" \
                    and ev.get("incarnation") == self.incarnation:
                self.port = int(ev["port"])
                self._announce.set()

    def wait_announce(self, timeout: float = 30.0) -> bool:
        return self._announce.wait(timeout)

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def _reap_pipe(self) -> None:
        # The stdout reader thread exits at EOF once the child is dead;
        # close the pipe fd explicitly (the conftest fd-leak guard's
        # discipline — teardown must not lean on GC).
        if self._reader is not None:
            self._reader.join(timeout=5.0)
        if self.proc is not None and self.proc.stdout is not None:
            try:
                self.proc.stdout.close()
            except OSError:
                pass

    def kill(self) -> None:
        if self.alive():
            os.kill(self.proc.pid, signal.SIGKILL)
            self.proc.wait(timeout=10.0)
        self._reap_pipe()

    def stop(self, timeout: float = 10.0) -> None:
        if self.alive():
            self.proc.terminate()
            try:
                self.proc.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5.0)
        self._reap_pipe()


class ReplayServiceFleet:
    """Owner of the shard fleet: spawn, supervise (RespawnPolicy backoff
    + crash-loop quarantine), endpoints publication, and the chaos
    kill-shard hooks.  ``auto_respawn=False`` hands respawn timing to the
    caller (the smoke's deterministic mid-kill chain inspection).

    The fleet is ELASTIC: :meth:`grow` appends a fresh empty shard at
    the next slot range, :meth:`retire` removes the HIGHEST shard after
    a digest-proven handoff — drain, final committed chain, bit-exact
    restore proof, re-add into the survivors — so only uniform
    contiguous geometries ever exist and the client's ``index //
    shard_capacity`` routing stays exact through every resize.  Both are
    the :class:`~ape_x_dqn_tpu.autopilot.actuators.ReplayFleetActuator`
    surface.  With ``registry_addr`` set, every shard is announced to
    the fleet registry (kind ``replay_shard``) and membership — not the
    endpoints file — drives client/aggregator routing; the file is still
    written as the compat fallback.
    """

    def __init__(self, num_shards: int, capacity: int, obs_shape, *,
                 root_dir: str, priority_exponent: float = 0.6,
                 codec: str = "zlib", save_every_s: float = 2.0,
                 base_every: int = 16, endpoints_path: Optional[str] = None,
                 token: Optional[int] = None,
                 hot_frame_budget_bytes: int = 0,
                 registry_addr: Optional[Tuple[str, int]] = None,
                 heartbeat_s: float = 1.0,
                 auto_respawn: bool = True, respawn_base_s: float = 0.25,
                 respawn_max_s: float = 5.0, crash_loop_budget: int = 6,
                 rpc_delay_ms: float = 0.0, rpc_drop_rate: float = 0.0,
                 kill_shard_at_step: int = 0, chaos_seed: int = 0,
                 seed: int = 0, on_event=None):
        if num_shards < 1:
            raise ValueError("replay fleet needs >= 1 shard")
        if capacity % num_shards:
            raise ValueError(
                f"capacity {capacity} must divide evenly into "
                f"{num_shards} shards"
            )
        from ape_x_dqn_tpu.runtime.supervisor import RespawnPolicy

        # With a registry the fleet authenticates shards under the RUN
        # token (the registry's), so one credential covers discovery and
        # the replay RPC hello; standalone keeps the private random one.
        self.token = int(token) if token else (secrets.randbits(63) or 1)
        self.num_shards = int(num_shards)
        self.capacity = int(capacity)
        self.shard_capacity = self.capacity // self.num_shards
        self.obs_shape = tuple(int(d) for d in obs_shape)
        self.alpha = float(priority_exponent)
        self.save_every_s = float(save_every_s)
        self.base_every = int(base_every)
        self.hot_frame_budget_bytes = int(hot_frame_budget_bytes)
        self.rpc_delay_ms = float(rpc_delay_ms)
        self.rpc_drop_rate = float(rpc_drop_rate)
        self.chaos_seed = int(chaos_seed)
        self.root_dir = os.path.abspath(root_dir)
        root_dir = self.root_dir
        os.makedirs(root_dir, exist_ok=True)
        self.endpoints_path = endpoints_path or os.path.join(
            root_dir, "endpoints.json"
        )
        self.codec = codec
        self._on_event = on_event
        self._auto_respawn = bool(auto_respawn)
        self._respawn_policy = RespawnPolicy(
            base_s=respawn_base_s, max_s=respawn_max_s,
            budget=crash_loop_budget, seed=seed,
        )
        self._kill_at_step = int(kill_shard_at_step)
        self._kill_fired = False
        import random as _random

        self._chaos_rng = _random.Random(chaos_seed ^ 0x5A4D)
        self.shards = [self._make_shard(k) for k in range(self.num_shards)]
        self.respawns = 0
        self.kills = 0
        self.grows = 0
        self.retires = 0
        self.quarantined: set = set()
        self._registry_addr = registry_addr
        self._heartbeat_s = float(heartbeat_s)
        self._announcer: Optional[FleetAnnouncer] = None
        self._reshard_lock = threading.Lock()
        self._resharding = False
        self._retiring: Optional[int] = None   # supervisor must not respawn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _make_shard(self, sid: int) -> ReplayShardProcess:
        return ReplayShardProcess(
            sid, self.shard_capacity, self.obs_shape, token=self.token,
            root_dir=self.root_dir, priority_exponent=self.alpha,
            codec=self.codec, save_every_s=self.save_every_s,
            base_every=self.base_every,
            hot_frame_budget_bytes=self.hot_frame_budget_bytes,
            rpc_delay_ms=self.rpc_delay_ms,
            rpc_drop_rate=self.rpc_drop_rate,
            chaos_seed=self.chaos_seed + sid,
        )

    def _event(self, name: str, **fields) -> None:
        # Positional param deliberately NOT named ``kind``: the reshard
        # events carry a ``kind="grow"/"retire"`` field of their own.
        if self._on_event is not None:
            try:
                self._on_event(name, **fields)
            except Exception:  # noqa: BLE001 — observer callback must never break the fleet/client
                pass

    # -- endpoints ---------------------------------------------------------

    def write_endpoints(self) -> None:
        """Atomic publish (tmp + rename — the manifest discipline): the
        client's probe loop re-reads on mtime change."""
        doc = {
            "token": self.token,
            "codec": self.codec,
            "total_capacity": self.capacity,
            "shards": [
                {
                    "id": s.shard_id, "host": s.host,
                    "port": s.port if s.port is not None else -1,
                    "base": s.shard_id * self.shard_capacity,
                    "capacity": s.capacity,
                    "incarnation": s.incarnation,
                }
                for s in self.shards
            ],
        }
        tmp = self.endpoints_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.endpoints_path)

    # -- lifecycle ---------------------------------------------------------

    def _shard_doc(self, s: ReplayShardProcess,
                   draining: bool = False) -> dict:
        return member_doc(
            f"replay/shard{s.shard_id}", "replay_shard",
            host=s.host, port=s.port or 0,
            incarnation=s.incarnation,
            base=s.shard_id * self.shard_capacity,
            capacity=s.capacity, draining=draining,
        )

    def _announce_shard(self, s: ReplayShardProcess,
                        draining: bool = False) -> None:
        if self._announcer is not None:
            self._announcer.set_member(self._shard_doc(s, draining))
            self._announcer.poke()

    def start(self, timeout: float = 60.0) -> "ReplayServiceFleet":
        deadline = time.monotonic() + timeout
        for s in self.shards:
            s.spawn()
        for s in self.shards:
            if not s.wait_announce(max(1.0, deadline - time.monotonic())):
                raise TimeoutError(
                    f"replay shard {s.shard_id} never announced its port "
                    f"(see {s.ckpt_dir}/shard{s.shard_id}."
                    f"{s.incarnation}.log)"
                )
        self.write_endpoints()
        if self._registry_addr is not None:
            host, port = self._registry_addr
            self._announcer = FleetAnnouncer(
                host, int(port), token=self.token,
                member_id=member_id_for(f"replay-fleet-{os.getpid()}"),
                heartbeat_s=self._heartbeat_s, on_event=self._on_event,
            )
            for s in self.shards:
                self._announcer.set_member(self._shard_doc(s))
            self._announcer.start()
        if self._auto_respawn:
            self._thread = threading.Thread(
                target=self._supervise_loop, name="replay-fleet", daemon=True
            )
            self._thread.start()
        return self

    def respawn(self, shard_id: int, timeout: float = 60.0) -> None:
        """Respawn one shard now (fresh incarnation; recovers from its
        checkpoint chain) and republish endpoints + membership."""
        s = self.shards[shard_id]
        s.spawn()
        if not s.wait_announce(timeout):
            raise TimeoutError(
                f"respawned shard {shard_id} never announced"
            )
        self.respawns += 1
        self.write_endpoints()
        self._announce_shard(s)
        self._event("replay_shard_respawned", shard=shard_id,
                    incarnation=s.incarnation, port=s.port)

    # -- elastic resharding (the autopilot's replay actuator surface) ------

    def resharding(self) -> bool:
        with self._reshard_lock:
            return self._resharding

    def _begin_reshard(self) -> bool:
        with self._reshard_lock:
            if self._resharding:
                return False
            self._resharding = True
            return True

    def _end_reshard(self) -> None:
        with self._reshard_lock:
            self._resharding = False

    def grow(self, timeout: float = 60.0) -> Optional[int]:
        """Split: append one fresh EMPTY shard at the next slot range
        (sid = current count — geometries stay uniform and contiguous,
        so client routing math survives).  Returns the new sid, or None
        when a reshard is already in flight or the spawn failed."""
        if not self._begin_reshard():
            return None
        sid = self.num_shards
        try:
            self._event("reshard_started", kind="grow", shard=sid,
                        shards_from=self.num_shards,
                        shards_to=self.num_shards + 1)
            s = self._make_shard(sid)
            # A retired shard's old chain must not resurrect into the
            # NEW (empty) slot range: the handoff already moved that
            # data to the survivors.
            if os.path.isdir(s.ckpt_dir):
                shutil.rmtree(s.ckpt_dir, ignore_errors=True)
            s.spawn()
            if not s.wait_announce(timeout):
                s.stop()
                self._event("reshard_failed", kind="grow", shard=sid,
                            error="spawn timeout")
                return None
            self.shards.append(s)
            self.num_shards += 1
            self.capacity += self.shard_capacity
            self.grows += 1
            self.write_endpoints()
            self._announce_shard(s)
            self._event("reshard_done", kind="grow", shard=sid,
                        shards=self.num_shards, transferred=0,
                        lost=0, digest_ok=True)
            return sid
        finally:
            self._end_reshard()

    def retire(self, drain_grace_s: float = 0.5,
               timeout: float = 60.0) -> Optional[int]:
        """Merge: remove the HIGHEST shard via a digest-proven handoff —
        announce it draining (clients stop routing adds), let in-flight
        adds settle, fingerprint the live state (content crc), SIGTERM
        (the clean-stop path commits a final chain), restore the chain
        and PROVE it bit-exact against the live fingerprint, then re-add
        every held transition (priorities recovered from the p^α masses)
        into the survivors oldest-first.  Returns the retired sid, or
        None when the fleet is at one shard / a reshard is in flight /
        the proof failed (the shard respawns and the fleet stays put —
        an unproven handoff never discards data)."""
        if not self._begin_reshard():
            return None
        if self.num_shards <= 1:
            self._end_reshard()
            return None
        s = self.shards[-1]
        sid = s.shard_id
        try:
            if not s.alive() or sid in self.quarantined:
                self._event("reshard_failed", kind="retire", shard=sid,
                            error="shard not serving")
                return None
            self._event("reshard_started", kind="retire", shard=sid,
                        shards_from=self.num_shards,
                        shards_to=self.num_shards - 1)
            self._announce_shard(s, draining=True)
            time.sleep(max(0.0, drain_grace_s))
            # Live fingerprint — the proof anchor the restored chain
            # must reproduce bit for bit.
            src = ShardClient(
                sid, s.host, s.port, token=self.token,
                client_id=(os.getpid() << 16) ^ secrets.randbits(16),
                incarnation=s.incarnation, codec=self.codec,
            )
            try:
                src_digest = src.digest(with_crc=True,
                                        timeout=min(30.0, timeout))
            finally:
                src.close()
            # Clean stop: SIGTERM → server.close() → final committed
            # chain save (the shard CLI's teardown contract).
            self._retiring = sid
            s.stop(timeout=timeout)
            restored = self._restore_shard_state(s)
            d = restored.digest(with_crc=True)
            digest_ok = all(
                int(d[k]) == int(src_digest[k])
                for k in ("count", "cursor", "size", "crc")
            ) and abs(d["total_mass"] - src_digest["total_mass"]) <= 1e-6
            if not digest_ok:
                # Unproven chain: put the shard BACK (its chain is still
                # the newest committed state) and abort the merge.
                self._event("reshard_failed", kind="retire", shard=sid,
                            error="handoff digest mismatch",
                            src=src_digest, restored=d)
                self.respawn(sid, timeout=timeout)
                self._announce_shard(s, draining=False)
                return None
            # Geometry shrinks BEFORE the transfer: clients must never
            # route new work at the vacated range while its transitions
            # re-enter under survivor indices.
            self.shards.pop()
            self.num_shards -= 1
            self.capacity -= self.shard_capacity
            self.write_endpoints()
            if self._announcer is not None:
                self._announcer.remove_member(f"replay/shard{sid}")
                self._announcer.poke()
            transferred, lost = self._transfer_out(restored, timeout)
            self.retires += 1
            # Park the consumed chain: a later grow() of this sid must
            # start EMPTY, not resurrect handed-off data.
            parked = s.ckpt_dir + ".retired"
            shutil.rmtree(parked, ignore_errors=True)
            try:
                os.rename(s.ckpt_dir, parked)
            except OSError:
                shutil.rmtree(s.ckpt_dir, ignore_errors=True)
            self._event("reshard_done", kind="retire", shard=sid,
                        shards=self.num_shards, transferred=transferred,
                        lost=lost, digest_ok=True,
                        crc=int(src_digest["crc"]),
                        count=int(src_digest["count"]))
            return sid
        except Exception as e:  # noqa: BLE001 — a failed handoff is a typed event; the fleet must survive it
            self._event("reshard_failed", kind="retire", shard=sid,
                        error=f"{type(e).__name__}: {e}")
            return None
        finally:
            self._retiring = None
            self._end_reshard()

    def _restore_shard_state(self, s: ReplayShardProcess):
        """The retired shard's committed chain, restored in-process (a
        plain dense replay — the tiered store materializes identically
        through ``get``, so digests stay comparable)."""
        from ape_x_dqn_tpu.replay.buffer import PrioritizedReplay
        from ape_x_dqn_tpu.utils.checkpoint_inc import (
            load_incremental_replay,
        )

        replay = PrioritizedReplay(self.shard_capacity, self.obs_shape,
                                   priority_exponent=self.alpha)
        load_incremental_replay(s.ckpt_dir, replay, fallback=False)
        return replay

    def _transfer_out(self, replay, timeout: float) -> Tuple[int, int]:
        """Re-add every transition of a restored (already-removed) shard
        into the survivors, oldest-first so survivor ring evictions —
        if any — fall on the oldest data, the loss order replay already
        lives with.  Returns (transferred, lost)."""
        size = int(replay.size())
        if size == 0:
            return 0, 0
        state = replay.state_dict()
        count, cursor = int(state["count"]), int(state["cursor"])
        if count > replay.capacity:      # wrapped ring: oldest at cursor
            order = (cursor + np.arange(size)) % size
        else:
            order = np.arange(size)
        mass = np.asarray(state["tree_priorities"], np.float64)
        if self.alpha > 0:
            prio = np.power(np.maximum(mass, 1e-12), 1.0 / self.alpha)
        else:
            prio = np.ones_like(mass)
        clients = [
            ShardClient(
                p.shard_id, p.host, p.port, token=self.token,
                client_id=(os.getpid() << 16) ^ secrets.randbits(16),
                incarnation=p.incarnation, codec=self.codec,
            )
            for p in self.shards
        ]
        transferred = lost = 0
        try:
            batch = 256
            for pos, off in enumerate(range(0, size, batch)):
                rows = order[off:off + batch]
                body = encode_body(
                    {
                        "prio": prio[rows],
                        "obs": np.asarray(state["obs"])[rows],
                        "action": np.asarray(state["action"])[rows],
                        "reward": np.asarray(state["reward"])[rows],
                        "discount": np.asarray(state["discount"])[rows],
                        "next_obs": np.asarray(state["next_obs"])[rows],
                    },
                    codec=_CODEC_IDS[self.codec], dedup=True,
                )
                sent = False
                for attempt in range(len(clients)):
                    c = clients[(pos + attempt) % len(clients)]
                    try:
                        c.request(OP_ADD, body, timeout=timeout)
                        sent = True
                        break
                    except (ReplayShardUnavailable, ReplayRpcError):
                        continue
                if sent:
                    transferred += len(rows)
                else:
                    lost += len(rows)
        finally:
            for c in clients:
                c.close()
        return transferred, lost

    def kill(self, shard_id: int) -> dict:
        s = self.shards[shard_id]
        pid = s.pid
        s.kill()
        self.kills += 1
        rec = {"fault": "kill_shard", "shard": shard_id, "pid": pid}
        self._event("replay_shard_killed", **rec)
        return rec

    def kill_random(self, rng=None) -> dict:
        rng = rng or self._chaos_rng
        live = [s.shard_id for s in self.shards if s.alive()]
        if not live:
            return {"fault": "kill_shard", "skipped": "no live shards"}
        return self.kill(live[rng.randrange(len(live))])

    def maybe_kill_at_step(self, step: int) -> Optional[dict]:
        """The ``chaos.kill_shard_at_step`` drill: fire once, seeded
        victim, when the driver's step counter first crosses the mark."""
        if not self._kill_at_step or self._kill_fired \
                or step < self._kill_at_step:
            return None
        self._kill_fired = True
        return self.kill_random()

    def _supervise_loop(self) -> None:
        from ape_x_dqn_tpu.runtime.supervisor import QUARANTINE, RESPAWN

        reported: set = set()
        while not self._stop.wait(0.1):
            for s in list(self.shards):
                sid = s.shard_id
                if sid == self._retiring:
                    # Mid-handoff: the retire path owns this shard's
                    # lifecycle — a supervisor respawn here would fork
                    # the slot range's history.
                    continue
                if s.alive() or sid in self.quarantined:
                    reported.discard(sid)
                    continue
                if sid not in reported:
                    reported.add(sid)
                    if self._respawn_policy.on_death(sid) == QUARANTINE:
                        self.quarantined.add(sid)
                        self._event("replay_shard_quarantined", shard=sid)
                        continue
                if self._respawn_policy.decide(sid) == RESPAWN:
                    try:
                        self.respawn(sid)
                        reported.discard(sid)
                    except (TimeoutError, OSError) as e:
                        self._event("replay_shard_respawn_failed",
                                    shard=sid, error=str(e))
                        self._respawn_policy.on_death(sid)

    def stats(self) -> dict:
        shards = list(self.shards)
        return {
            "shards": self.num_shards,
            "alive": sum(1 for s in shards if s.alive()),
            "respawns": self.respawns,
            "kills": self.kills,
            "grows": self.grows,
            "retires": self.retires,
            "resharding": self.resharding(),
            "quarantined": sorted(self.quarantined),
            "incarnations": {
                str(s.shard_id): s.incarnation for s in shards
            },
        }

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
        if self._announcer is not None:
            self._announcer.close(leave=True)
            self._announcer = None
        for s in list(self.shards):
            s.stop()


# ---------------------------------------------------------------------------
# Shard CLI: `python -m ape_x_dqn_tpu.replay.service --shard-id K ...`
# ---------------------------------------------------------------------------


def _emit_line(**fields) -> None:
    sys.stdout.write(json.dumps(fields) + "\n")
    sys.stdout.flush()


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="replay-shard", description=__doc__)
    ap.add_argument("--shard-id", type=int, required=True)
    ap.add_argument("--capacity", type=int, required=True)
    ap.add_argument("--obs-shape", required=True,
                    help="comma-separated, e.g. 84,84,1")
    ap.add_argument("--alpha", type=float, default=0.6)
    ap.add_argument("--token", type=int, default=0)
    ap.add_argument("--incarnation", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--codec", default="zlib",
                    choices=("off", "zlib", "auto"))
    ap.add_argument("--ckpt-dir", default="")
    ap.add_argument("--save-every-s", type=float, default=2.0)
    ap.add_argument("--base-every", type=int, default=16)
    ap.add_argument("--hot-frame-budget-bytes", type=int, default=0,
                    help="replay.service_hot_frame_budget_bytes: >0 hosts "
                    "the shard's replay on the tiered (spill-backed) "
                    "store, capping hot frame DRAM at this many bytes")
    ap.add_argument("--max-request-bytes", type=int,
                    default=_DEFAULT_MAX_FRAME)
    ap.add_argument("--rpc-delay-ms", type=float, default=0.0)
    ap.add_argument("--rpc-drop-rate", type=float, default=0.0)
    ap.add_argument("--chaos-seed", type=int, default=0)
    args = ap.parse_args(argv)

    from ape_x_dqn_tpu.replay.buffer import PrioritizedReplay

    obs_shape = tuple(int(d) for d in args.obs_shape.split(","))
    tier_kw = {}
    if args.hot_frame_budget_bytes > 0:
        # Spill-backed shard: the cold files live beside the chain (one
        # spill dir per incarnation-independent shard home).
        spill_dir = os.path.join(args.ckpt_dir or ".", "spill")
        os.makedirs(spill_dir, exist_ok=True)
        tier_kw = dict(hot_frame_budget_bytes=args.hot_frame_budget_bytes,
                       spill_dir=spill_dir)
    replay = PrioritizedReplay(args.capacity, obs_shape,
                               priority_exponent=args.alpha, **tier_kw)
    # Recovery: a respawned incarnation walks its own chain back to the
    # newest committed state — bit-exact (digest announced below) or a
    # typed degraded_restore from the fallback rungs, never silent.
    restored_step = None
    if args.ckpt_dir:
        from ape_x_dqn_tpu.utils.checkpoint_inc import (
            load_incremental_replay,
        )

        try:
            restored_step = load_incremental_replay(
                args.ckpt_dir, replay, fallback=True,
                on_event=lambda ev: _emit_line(**ev),
            )
        except Exception as e:  # noqa: BLE001 — typed failure, never silent
            _emit_line(event="replay_shard_restore_failed",
                       shard=args.shard_id,
                       error=f"{type(e).__name__}: {e}")
            return 2
        if restored_step is not None:
            d = replay.digest(with_crc=True)
            _emit_line(event="replay_shard_recovered", shard=args.shard_id,
                       incarnation=args.incarnation, step=restored_step,
                       **d)
    chaos = None
    if args.rpc_delay_ms or args.rpc_drop_rate:
        from ape_x_dqn_tpu.obs.chaos import RpcChaos

        chaos = RpcChaos(delay_ms=args.rpc_delay_ms,
                         drop_rate=args.rpc_drop_rate,
                         seed=args.chaos_seed)
    server = ReplayShardServer(
        replay, args.shard_id, incarnation=args.incarnation,
        token=args.token, host=args.host, port=args.port, codec=args.codec,
        max_request_bytes=args.max_request_bytes,
        ckpt_dir=args.ckpt_dir or None, save_every_s=args.save_every_s,
        base_every=args.base_every, chaos=chaos,
        on_event=lambda kind, **f: _emit_line(event=kind, **f),
    )
    server.start()
    _emit_line(event="replay_shard_listen", shard=args.shard_id,
               incarnation=args.incarnation, port=server.port,
               pid=os.getpid(), capacity=args.capacity,
               restored_step=restored_step)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    while not stop.wait(0.25):
        pass
    server.close()
    _emit_line(event="replay_shard_stopped", shard=args.shard_id,
               **{k: v for k, v in server.stats().items()
                  if k in ("requests", "torn_frames", "add_dups")})
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
