"""Frame-dedup device replay — the HBM ring storing each frame ONCE.

The double-store HBM ring (replay/device.py) carries ``obs`` AND
``next_obs`` — the 2× that made config3's 2M-slot device ring exceed a
16 GB chip (2M × 84×84 × 2 ≈ 28 GB; round-4 verdict items 1a/weakness 3).
This module is its dedup twin: a FRAME ring of ``frame_capacity``
observations plus per-transition int32 frame references, cutting the HBM
footprint to ~frame_ratio/2 of the double-store (2M slots ≈ 16.5 GB →
feasible per-chip at dp≥2 with the sharded builder in
replay/device_dedup_dp.py).

Reference addressing under XLA's int32 world:
  * frame sequence numbers live modulo ``Q = (2^30 // frame_capacity) ·
    frame_capacity`` — a multiple of the ring size, so ``slot = seq %
    frame_capacity`` stays consistent across the seq wrap, with every
    intermediate int32-safe and NO int64 anywhere in the graph.  The host
    stager keeps true int64 counters and ships refs already reduced mod Q.
  * liveness is the wrap-aware age ``(fcount − ref) mod Q ≤ frame_capacity``.
    The ingest op sweeps the whole mass vector with that test, so a
    transition whose frames were overwritten is unsampleable from the same
    program that overwrote them — the ring can never pair stale metadata
    with recycled pixels.  (Ages stay ≪ Q because the sweep runs every
    ingest; a mass-zero slot cannot resurrect — restamps only touch
    sampled slots, and dead slots are never sampled.)

Sampling/IS-weight law, batched restamp, and the K-step fused scan are
shared with the double-store via ``fused_scan_body(sample_many_fn=...)``
(replay/device.py) — the two layouts cannot drift semantically.  Equal-
semantics oracle: tests/test_device_dedup.py pins the dedup fused step
against the double-store fused step on an identical ingest stream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from ape_x_dqn_tpu.replay.device import fused_scan_body
from ape_x_dqn_tpu.types import NStepTransition, PrioritizedBatch


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


@struct.dataclass
class DedupDeviceReplayState:
    frames: jax.Array    # uint8 [Cf, *obs_shape] — each unique frame once
    obs_ref: jax.Array   # int32 [C] — S_t frame seq (mod Q)
    next_ref: jax.Array  # int32 [C] — S_{t+n} frame seq (mod Q)
    action: jax.Array    # int32 [C]
    reward: jax.Array    # float32 [C]
    discount: jax.Array  # float32 [C]
    mass: jax.Array      # float32 [C] — p^α, 0 marks empty/dead
    cursor: jax.Array    # int32 [] — transition ring position
    count: jax.Array     # int32 [] — transitions ever added (saturating)
    fcount: jax.Array    # int32 [] — frame seq counter (mod Q)

    @property
    def capacity(self) -> int:
        return self.mass.shape[0]

    @property
    def frame_capacity(self) -> int:
        return self.frames.shape[0]

    @property
    def seq_modulus(self) -> int:
        # Largest multiple of the ring size below 2^30: every intermediate
        # (seq + block, seq − seq) stays strictly inside int32 with no
        # silent wraparound, and the ambiguity window (Q − Cf frames
        # between sweeps before an age could alias) is still ~10^9 —
        # sweeps run every ingest, thousands of frames apart at most.
        return ((1 << 30) // self.frames.shape[0]) * self.frames.shape[0]


def init_dedup_device_replay(
    capacity: int,
    obs_shape,
    frame_capacity: int | None = None,
    frame_ratio: float = 1.25,
    obs_dtype=jnp.uint8,
) -> DedupDeviceReplayState:
    """``frame_capacity`` defaults to ``round(capacity · frame_ratio)``
    (same sizing contract as the host DedupReplay — cover the emission's
    frame/transition arrival ratio or oldest transitions die early,
    gracefully)."""
    if frame_capacity is None:
        frame_capacity = max(1, int(round(capacity * frame_ratio)))
    return DedupDeviceReplayState(
        frames=jnp.zeros((frame_capacity, *obs_shape), obs_dtype),
        obs_ref=jnp.zeros((capacity,), jnp.int32),
        next_ref=jnp.zeros((capacity,), jnp.int32),
        action=jnp.zeros((capacity,), jnp.int32),
        reward=jnp.zeros((capacity,), jnp.float32),
        discount=jnp.zeros((capacity,), jnp.float32),
        mass=jnp.zeros((capacity,), jnp.float32),
        cursor=jnp.zeros((), jnp.int32),
        count=jnp.zeros((), jnp.int32),
        fcount=jnp.zeros((), jnp.int32),
    )


def dedup_device_add_frames(
    state: DedupDeviceReplayState, frames: jax.Array
) -> DedupDeviceReplayState:
    """Append a frame block (length static).  Advances ``fcount`` mod Q;
    the liveness sweep rides the TRANSITION ingest (the op that changes
    which rows could reference overwritten frames is the frame write, but
    rows only become visible via masses — sweeping once per txn ingest
    after the paired frame blocks keeps one pass per ingest cycle; the
    runtime always ships frames-then-transitions)."""
    U = frames.shape[0]
    Cf = state.frame_capacity
    if U > Cf:
        raise ValueError(f"frame block {U} exceeds frame ring {Cf}")
    Q = state.seq_modulus
    idx = ((state.fcount + jnp.arange(U, dtype=jnp.int32)) % Q) % Cf
    return state.replace(
        frames=state.frames.at[idx].set(frames),
        fcount=(state.fcount + U) % Q,
    )


def _age(state: DedupDeviceReplayState, ref: jax.Array) -> jax.Array:
    Q = state.seq_modulus
    return (state.fcount - ref) % Q


def dedup_device_add_transitions(
    state: DedupDeviceReplayState,
    obs_ref: jax.Array,      # int32 [M] absolute seqs mod Q (host-resolved)
    next_ref: jax.Array,
    action: jax.Array,
    reward: jax.Array,
    discount: jax.Array,
    priorities: jax.Array,
    priority_exponent: float = 0.6,
) -> DedupDeviceReplayState:
    """Ring-insert a transition block + the liveness sweep (one fused
    whole-vector pass: rows whose obs frame aged out of the ring get mass
    0 in the same program — see module docstring)."""
    M = priorities.shape[0]
    if M > state.capacity:
        raise ValueError(
            f"chunk of {M} transitions exceeds replay capacity {state.capacity}"
        )
    idx = (state.cursor + jnp.arange(M, dtype=jnp.int32)) % state.capacity
    mass = jnp.power(jnp.maximum(priorities.astype(jnp.float32), 1e-12),
                     priority_exponent)
    new = state.replace(
        obs_ref=state.obs_ref.at[idx].set(obs_ref.astype(jnp.int32)),
        next_ref=state.next_ref.at[idx].set(next_ref.astype(jnp.int32)),
        action=state.action.at[idx].set(action.astype(jnp.int32)),
        reward=state.reward.at[idx].set(reward),
        discount=state.discount.at[idx].set(discount),
        mass=state.mass.at[idx].set(mass),
        cursor=(state.cursor + M) % state.capacity,
        count=jnp.minimum(state.count + M, jnp.int32(1 << 30)),
    )
    # Sweep: obs_ref is each row's OLDEST frame (DedupChunk layout
    # contract), so one age test invalidates exactly the frame-dead rows.
    dead = _age(new, new.obs_ref) > new.frame_capacity
    return new.replace(mass=jnp.where(dead, 0.0, new.mass))


def dedup_sample_many(
    state: DedupDeviceReplayState,
    rng: jax.Array,
    num_batches: int,
    batch_size: int,
    beta: jax.Array | float = 0.4,
    axis_name: str | None = None,
) -> PrioritizedBatch:
    """Stratified PER sample over the dedup layout — identical law and IS
    weights to ``device_replay_sample_many`` (shared spec: the weight math
    below mirrors replay/device.py:146-169 line for line); only the frame
    gather goes through the ref indirection."""
    from ape_x_dqn_tpu.ops.pallas.sampling import sample_indices

    K, B = num_batches, batch_size
    total = jnp.sum(state.mass)
    bounds = total / B
    u = jax.random.uniform(rng, (K, B))
    targets = (jnp.arange(B, dtype=jnp.float32)[None, :] + u) * bounds
    targets = jnp.minimum(targets, total * (1.0 - 1e-7))
    idx = sample_indices(state.mass, targets.reshape(-1))      # [K*B]
    size_i = jnp.maximum(jnp.minimum(state.count, state.capacity), 1)
    idx = jnp.minimum(idx, size_i - 1)
    probs = state.mass[idx] / jnp.maximum(total, 1e-12)
    if axis_name is None:
        n_shards = 1
        size_global = size_i
    else:
        n_shards = jax.lax.psum(1, axis_name)
        size_global = jax.lax.psum(size_i, axis_name)
    weights = jnp.power(
        jnp.maximum(size_global.astype(jnp.float32) * probs / n_shards, 1e-12),
        -beta,
    ).reshape(K, B)
    wmax = jnp.max(weights, axis=1, keepdims=True)
    if axis_name is not None:
        wmax = jax.lax.pmax(wmax, axis_name)
    weights = weights / wmax
    idx2 = idx.reshape(K, B)
    Cf = state.frame_capacity
    obs = state.frames[state.obs_ref[idx] % Cf]
    next_obs = state.frames[state.next_ref[idx] % Cf]
    return PrioritizedBatch(
        transition=NStepTransition(
            obs=obs.reshape(K, B, *state.frames.shape[1:]),
            action=state.action[idx2],
            reward=state.reward[idx2],
            discount=state.discount[idx2],
            next_obs=next_obs.reshape(K, B, *state.frames.shape[1:]),
        ),
        indices=idx2,
        is_weights=weights.astype(jnp.float32),
    )


def build_dedup_fused_learn_step(
    train_step_fn,
    batch_size: int,
    steps_per_call: int = 1,
    priority_exponent: float = 0.6,
    target_sync_freq: int | None = 2500,
    include_ingest: bool = False,
    sample_ahead: bool = False,
    jit: bool = True,
):
    """The dedup twin of ``device.build_fused_learn_step`` — same K-step
    [sample → train → restamp] scan (literally the same ``fused_scan_body``,
    parameterized by the dedup sampler), same hoisted target sync.

    ``include_ingest=True`` prepends a fixed-shape frame+transition ingest
    to each call (the bench/bulk path); the async runtime uses False and
    ingests on its own clock via the two add ops above.

    Returns (with ingest)
    ``fn(train_state, replay_state, frames, obs_ref, next_ref, action,
    reward, discount, chunk_priorities, beta, rng)`` or (without)
    ``fn(train_state, replay_state, beta, rng)``; both states donated.
    """

    def fused(train_state, replay_state, beta, rng):
        return fused_scan_body(
            train_step_fn, train_state, replay_state, beta, rng,
            steps_per_call=steps_per_call, batch_size=batch_size,
            priority_exponent=priority_exponent,
            target_sync_freq=target_sync_freq, sample_ahead=sample_ahead,
            sample_many_fn=dedup_sample_many,
        )

    if include_ingest:
        inner = fused

        def fused_ingest(train_state, replay_state, frames, obs_ref,
                         next_ref, action, reward, discount,
                         chunk_priorities, beta, rng):
            replay_state = dedup_device_add_frames(replay_state, frames)
            replay_state = dedup_device_add_transitions(
                replay_state, obs_ref, next_ref, action, reward, discount,
                chunk_priorities, priority_exponent,
            )
            return inner(train_state, replay_state, beta, rng)

        fused = fused_ingest

    if jit:
        return jax.jit(fused, donate_argnums=(0, 1))
    return fused
