"""Central prioritized replay — array-backed ring buffer + sum-tree.

Capability parity with the reference's ``ReplayMemory`` (replay.py:8-83), with
the intended semantics and none of its defects (SURVEY §2.8):

  * proportional prioritization p^α (replay.py:24-30) — but via a sum-tree
    (O(log N) sample/update) instead of a flat dict + O(N·S) scan;
  * priority upsert from the learner (replay.py:32-42) — per-transition, not
    collapsed to a single value;
  * capacity-bounded FIFO eviction (replay.py:71-80) — implicit in the ring
    cursor, and a slot's priority dies with its data (the reference leaks
    stale keys forever);
  * importance-sampling weights with annealed β — the reference's README-TODO
    (config key parameters.json:30 read by nothing) built as a first-class
    capability.

Storage is preallocated numpy: frames stay uint8 end-to-end (a 2M-slot Atari
buffer is ~28 GB as bytes; float32 would be 4×), scalars in flat arrays.
Identity is the slot index — the wire format the learner echoes back with new
priorities (types.PrioritizedBatch.indices).

Thread-safety: one mutex around mutation and sampling.  The Ape-X access
pattern (many writers, one sampler) hits this lock with *batches* (an actor
chunk or a learner batch at a time), so lock traffic is O(steps/batch), not
O(steps) — the discipline that keeps the central replay off the critical path
(SURVEY §7 "hard parts" #1).
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ape_x_dqn_tpu.replay.sum_tree import SumTree
from ape_x_dqn_tpu.types import NStepTransition, PrioritizedBatch


class PrioritizedReplay:
    """Prioritized n-step transition store.

    Args:
      capacity: max transitions held (the reference's ``soft_capacity``,
        parameters.json:28 — hard here: the ring never exceeds it).
      obs_shape: per-frame observation shape, e.g. (84, 84, 1).
      priority_exponent: α in p^α (reference parameters.json:29, default 0.6).
      obs_dtype: storage dtype for frames (uint8 default).
      sum_tree_cls: injectable tree implementation; default picks the native
        C++ core (~10× the numpy tree's sample+update throughput at 2M slots)
        when the toolchain allows, numpy otherwise.
    """

    def __init__(
        self,
        capacity: int,
        obs_shape,
        priority_exponent: float = 0.6,
        obs_dtype=np.uint8,
        sum_tree_cls=None,
    ):
        if sum_tree_cls is None:
            from ape_x_dqn_tpu.replay.native import default_sum_tree_cls

            sum_tree_cls = default_sum_tree_cls()
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = int(capacity)
        self.alpha = float(priority_exponent)
        self._obs = np.zeros((capacity, *obs_shape), dtype=obs_dtype)
        self._next_obs = np.zeros((capacity, *obs_shape), dtype=obs_dtype)
        self._action = np.zeros((capacity,), dtype=np.int32)
        self._reward = np.zeros((capacity,), dtype=np.float32)
        self._discount = np.zeros((capacity,), dtype=np.float32)
        self._tree = sum_tree_cls(capacity)
        self._cursor = 0
        self._count = 0  # total transitions ever added
        self._lock = threading.Lock()

    # -- write path (actors / drain) ------------------------------------

    def add(self, priorities: np.ndarray, batch: NStepTransition) -> np.ndarray:
        """Insert a batch with actor-computed initial priorities
        (reference replay.py:59-69 ``add(priorities, xp_batch)``).

        Overwrites the oldest slots when full (FIFO).  Returns the slot
        indices written.
        """
        priorities = np.asarray(priorities, dtype=np.float64)
        n = priorities.shape[0]
        if n == 0:
            return np.zeros((0,), np.int64)
        if n > self.capacity:
            raise ValueError(f"batch of {n} exceeds capacity {self.capacity}")
        with self._lock:
            idx = (self._cursor + np.arange(n)) % self.capacity
            self._obs[idx] = batch.obs
            self._next_obs[idx] = batch.next_obs
            self._action[idx] = batch.action
            self._reward[idx] = batch.reward
            self._discount[idx] = batch.discount
            self._tree.set(idx, np.power(np.maximum(priorities, 1e-12), self.alpha))
            self._cursor = int((self._cursor + n) % self.capacity)
            self._count += n
            return idx

    # -- read path (learner) --------------------------------------------

    def sample(
        self,
        batch_size: int,
        beta: float = 0.4,
        rng: Optional[np.random.Generator] = None,
    ) -> PrioritizedBatch:
        """Stratified proportional sample with IS weights.

        P(i) = p_i^α / Σ p^α;  w_i = (N · P(i))^−β, normalized by max w
        (the standard PER correction the reference lists as TODO, β from
        parameters.json:30).
        """
        rng = rng or np.random.default_rng()
        with self._lock:
            size = min(self._count, self.capacity)
            if size == 0:
                raise ValueError("cannot sample from an empty replay")
            idx = self._tree.sample_stratified(batch_size, rng)
            mass = self._tree.get(idx)
            total = self._tree.total
            transition = NStepTransition(
                obs=self._obs[idx].copy(),
                action=self._action[idx].copy(),
                reward=self._reward[idx].copy(),
                discount=self._discount[idx].copy(),
                next_obs=self._next_obs[idx].copy(),
            )
        probs = mass / total
        weights = np.power(size * np.maximum(probs, 1e-12), -beta)
        weights = (weights / weights.max()).astype(np.float32)
        return PrioritizedBatch(
            transition=transition,
            indices=idx.astype(np.int32),
            is_weights=weights,
        )

    def update_priorities(self, indices: np.ndarray, priorities: np.ndarray) -> None:
        """Learner priority feedback (reference ``set_priorities``,
        replay.py:32 — here per-transition and O(B log N)).

        If a sampled slot was recycled between sample and update, the fresh
        transition briefly carries the old transition's updated priority —
        a benign, self-correcting race (the slot is resampled and restamped
        within a few steps), and the same whole-value-atomicity discipline
        the reference relies on (SURVEY §5 race detection).
        """
        indices = np.asarray(indices, dtype=np.int64)
        priorities = np.asarray(priorities, dtype=np.float64)
        if indices.size == 0:
            return
        with self._lock:
            self._tree.set(
                indices, np.power(np.maximum(priorities, 1e-12), self.alpha)
            )

    # -- misc ------------------------------------------------------------

    def size(self) -> int:
        """Current number of stored transitions (reference replay.py:82)."""
        with self._lock:
            return min(self._count, self.capacity)

    @property
    def total_added(self) -> int:
        return self._count

    def max_priority(self) -> float:
        with self._lock:
            m = self._tree.max_priority()
        return float(m ** (1.0 / self.alpha)) if m > 0 else 1.0

    # -- snapshot (checkpointing) ----------------------------------------

    def state_dict(self) -> dict:
        """Snapshot for checkpoint/resume (the reference checkpoints nothing
        of the replay — SURVEY §5 checkpoint/resume)."""
        with self._lock:
            size = min(self._count, self.capacity)
            idx = np.arange(size)
            return {
                "obs": self._obs[:size].copy(),
                "next_obs": self._next_obs[:size].copy(),
                "action": self._action[:size].copy(),
                "reward": self._reward[:size].copy(),
                "discount": self._discount[:size].copy(),
                "tree_priorities": self._tree.get(idx),
                "cursor": self._cursor,
                "count": self._count,
            }

    def load_state_dict(self, state: dict) -> None:
        with self._lock:
            size = state["obs"].shape[0]
            if size > self.capacity:
                raise ValueError("snapshot larger than capacity")
            # Clear everything first so a restore into a warm buffer cannot
            # leave stale transitions sampleable past the snapshot region.
            self._tree.set(
                np.arange(self.capacity), np.zeros(self.capacity, np.float64)
            )
            self._obs[:size] = state["obs"]
            self._next_obs[:size] = state["next_obs"]
            self._action[:size] = state["action"]
            self._reward[:size] = state["reward"]
            self._discount[:size] = state["discount"]
            self._tree.set(np.arange(size), state["tree_priorities"])
            self._cursor = int(state["cursor"]) % self.capacity
            self._count = int(state["count"])
