"""Central prioritized replay — array-backed ring buffer + sum-tree.

Capability parity with the reference's ``ReplayMemory`` (replay.py:8-83), with
the intended semantics and none of its defects (SURVEY §2.8):

  * proportional prioritization p^α (replay.py:24-30) — but via a sum-tree
    (O(log N) sample/update) instead of a flat dict + O(N·S) scan;
  * priority upsert from the learner (replay.py:32-42) — per-transition, not
    collapsed to a single value;
  * capacity-bounded FIFO eviction (replay.py:71-80) — implicit in the ring
    cursor, and a slot's priority dies with its data (the reference leaks
    stale keys forever);
  * importance-sampling weights with annealed β — the reference's README-TODO
    (config key parameters.json:30 read by nothing) built as a first-class
    capability.

Storage is preallocated numpy: frames stay uint8 end-to-end (a 2M-slot Atari
buffer is ~28 GB as bytes; float32 would be 4×), scalars in flat arrays.
Identity is the slot index — the wire format the learner echoes back with new
priorities (types.PrioritizedBatch.indices).

Thread-safety: one mutex around mutation and sampling.  The Ape-X access
pattern (many writers, one sampler) hits this lock with *batches* (an actor
chunk or a learner batch at a time), so lock traffic is O(steps/batch), not
O(steps) — the discipline that keeps the central replay off the critical path
(SURVEY §7 "hard parts" #1).
"""

from __future__ import annotations

import threading
import zlib
from typing import Optional

import numpy as np

from ape_x_dqn_tpu.replay.sum_tree import SumTree
from ape_x_dqn_tpu.types import NStepTransition, PrioritizedBatch


class RawFrameStore:
    """Preallocated ndarray frame storage — the default.

    The encode/put_encoded split exists so ``PrioritizedReplay.add`` can do
    any per-frame work (a no-op here; deflate for the compressed store)
    OUTSIDE the replay lock.
    """

    compressed = False

    def __init__(self, capacity: int, frame_shape, dtype=np.uint8):
        self._arr = np.zeros((capacity, *frame_shape), dtype=dtype)
        self.shape = tuple(frame_shape)
        self.dtype = np.dtype(dtype)

    def encode(self, frames: np.ndarray):
        return frames

    def put_encoded(self, idx: np.ndarray, encoded) -> None:
        self._arr[idx] = encoded

    def put(self, idx: np.ndarray, frames: np.ndarray) -> None:
        self.put_encoded(idx, self.encode(frames))

    def get(self, idx: np.ndarray) -> np.ndarray:
        # Advanced indexing already allocates a fresh array — no copy.
        return self._arr[idx]

    def nbytes(self) -> int:
        return self._arr.nbytes


class TieredFrameStore:
    """Frame store over a ``TieredFrameRing`` (replay/tiered.py): the
    double-store's answer to ``replay.hot_frame_budget_bytes``.  Slot
    indices map 1:1 onto ring slots; least-recently-sampled spans spill
    to the CRC-framed cold file and fault back on ``get``.

    Snapshots still materialize through ``get`` (the double-store has no
    cold-ref checkpoint leg — that optimization lives on the dedup path,
    where paper-scale rings are); the tier here is purely a DRAM cap on
    the live buffer.
    """

    compressed = False

    def __init__(self, capacity: int, frame_shape, dtype=np.uint8, *,
                 hot_budget_bytes: int, spill_path: str,
                 span_frames: int = 0, watermark_high: float = 1.0,
                 watermark_low: float = 0.9):
        from ape_x_dqn_tpu.replay.tiered import TieredFrameRing

        self.ring = TieredFrameRing(
            capacity, frame_shape, dtype=dtype,
            hot_budget_bytes=hot_budget_bytes, spill_path=spill_path,
            span_frames=span_frames, watermark_high=watermark_high,
            watermark_low=watermark_low,
        )
        self.shape = self.ring.frame_shape
        self.dtype = self.ring.dtype

    def encode(self, frames: np.ndarray):
        return frames

    def put_encoded(self, idx: np.ndarray, encoded) -> None:
        self.ring.put(np.asarray(idx, np.int64), encoded)

    def put(self, idx: np.ndarray, frames: np.ndarray) -> None:
        self.put_encoded(idx, self.encode(frames))

    def get(self, idx: np.ndarray) -> np.ndarray:
        return self.ring.get(np.asarray(idx, np.int64))

    def nbytes(self) -> int:
        return self.ring.hot_bytes


class CompressedFrameStore:
    """Per-slot zlib-compressed frame storage — the reference's own README
    TODO ("compressing the frames", reference README.md:24) as an opt-in
    memory/CPU trade (SURVEY §7 stage-4 memory option).

    Structured frames (Atari-like) compress 3-10×; the cost is one
    deflate per stored frame (off-lock, via ``encode``) and one inflate per
    sampled row, on the host path only (the HBM device replay is
    unaffected).  Level 1 is the right spot: >90% of the ratio at a
    fraction of level 6's CPU.
    """

    compressed = True

    def __init__(self, capacity: int, frame_shape, dtype=np.uint8, level: int = 1):
        self._slots: list = [None] * capacity
        self.shape = tuple(frame_shape)
        self.dtype = np.dtype(dtype)
        self.level = int(level)

    def encode(self, frames: np.ndarray) -> list:
        frames = np.asarray(frames, self.dtype)
        return [zlib.compress(frames[i].tobytes(), self.level)
                for i in range(frames.shape[0])]

    def put_encoded(self, idx: np.ndarray, encoded: list) -> None:
        for i, k in enumerate(idx):
            self._slots[int(k)] = encoded[i]

    def put(self, idx: np.ndarray, frames: np.ndarray) -> None:
        self.put_encoded(idx, self.encode(frames))

    def get(self, idx: np.ndarray) -> np.ndarray:
        out = np.empty((len(idx), *self.shape), self.dtype)
        for i, k in enumerate(idx):
            out[i] = np.frombuffer(
                zlib.decompress(self._slots[int(k)]), self.dtype
            ).reshape(self.shape)
        return out

    def export_blobs(self, size: int) -> tuple:
        """(blob uint8 [sum lens], lens int64 [size]) — the deflated slots
        verbatim, so snapshots never materialize the dense buffer (the
        whole point of this store is that the dense form doesn't fit)."""
        blobs = self._slots[:size]
        lens = np.array([len(b) for b in blobs], np.int64)
        return np.frombuffer(b"".join(blobs), np.uint8).copy(), lens

    def import_blobs(self, blob: np.ndarray, lens: np.ndarray) -> None:
        raw = blob.tobytes()
        off = 0
        for i, n in enumerate(lens):
            self._slots[i] = raw[off:off + int(n)]
            off += int(n)

    def export_blobs_idx(self, idx: np.ndarray) -> tuple:
        """Deflated slots at arbitrary indices (dirty-span checkpointing)."""
        blobs = [self._slots[int(k)] for k in idx]
        lens = np.array([len(b) for b in blobs], np.int64)
        joined = b"".join(blobs)
        return np.frombuffer(joined, np.uint8).copy(), lens

    def import_blobs_idx(self, idx: np.ndarray, blob: np.ndarray,
                         lens: np.ndarray) -> None:
        raw = blob.tobytes()
        off = 0
        for k, n in zip(idx, lens):
            self._slots[int(k)] = raw[off:off + int(n)]
            off += int(n)

    def nbytes(self) -> int:
        return sum(len(s) for s in self._slots if s is not None)


class PrioritizedReplay:
    """Prioritized n-step transition store.

    Args:
      capacity: max transitions held (the reference's ``soft_capacity``,
        parameters.json:28 — hard here: the ring never exceeds it).
      obs_shape: per-frame observation shape, e.g. (84, 84, 1).
      priority_exponent: α in p^α (reference parameters.json:29, default 0.6).
      obs_dtype: storage dtype for frames (uint8 default).
      sum_tree_cls: injectable tree implementation; default picks the native
        C++ core (~10× the numpy tree's sample+update throughput at 2M slots)
        when the toolchain allows, numpy otherwise.
    """

    def __init__(
        self,
        capacity: int,
        obs_shape,
        priority_exponent: float = 0.6,
        obs_dtype=np.uint8,
        sum_tree_cls=None,
        frame_compression: bool = False,
        hot_frame_budget_bytes: int = 0,
        spill_dir=None,
        spill_span_frames: int = 0,
        spill_watermark_high: float = 1.0,
        spill_watermark_low: float = 0.9,
    ):
        if sum_tree_cls is None:
            from ape_x_dqn_tpu.replay.native import default_sum_tree_cls

            sum_tree_cls = default_sum_tree_cls()
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = int(capacity)
        self.alpha = float(priority_exponent)
        if hot_frame_budget_bytes > 0:
            # Tiered double-store: obs and next_obs each get half the hot
            # budget and their own spill file (config.py
            # replay.hot_frame_budget_bytes; mutually exclusive with
            # frame_compression at validation).
            import os

            if frame_compression:
                raise ValueError(
                    "hot_frame_budget_bytes and frame_compression are "
                    "mutually exclusive"
                )
            if spill_dir is None:
                raise ValueError("tiered replay needs a spill_dir")
            half = max(1, int(hot_frame_budget_bytes) // 2)
            tier_kw = dict(
                span_frames=spill_span_frames,
                watermark_high=spill_watermark_high,
                watermark_low=spill_watermark_low,
            )
            self._obs = TieredFrameStore(
                capacity, obs_shape, obs_dtype, hot_budget_bytes=half,
                spill_path=os.path.join(spill_dir, "obs.cold"), **tier_kw,
            )
            self._next_obs = TieredFrameStore(
                capacity, obs_shape, obs_dtype, hot_budget_bytes=half,
                spill_path=os.path.join(spill_dir, "next_obs.cold"),
                **tier_kw,
            )
        else:
            store_cls = (CompressedFrameStore if frame_compression
                         else RawFrameStore)
            self._obs = store_cls(capacity, obs_shape, obs_dtype)
            self._next_obs = store_cls(capacity, obs_shape, obs_dtype)
        self._action = np.zeros((capacity,), dtype=np.int32)
        self._reward = np.zeros((capacity,), dtype=np.float32)
        self._discount = np.zeros((capacity,), dtype=np.float32)
        self._tree = sum_tree_cls(capacity)
        self._cursor = 0
        self._count = 0  # total transitions ever added
        self._lock = threading.Lock()
        # Incremental-checkpoint dirty tracking (utils/checkpoint_inc):
        # _ckpt marks (count, cursor) at the last delta snapshot; _dirty
        # accumulates restamped index arrays since then.  None = tracking
        # off → the next delta_state_dict emits a full base.
        self._ckpt = None
        self._dirty: list = []
        self._dirty_rows = 0

    # -- write path (actors / drain) ------------------------------------

    def add(self, priorities: np.ndarray, batch: NStepTransition) -> np.ndarray:
        """Insert a batch with actor-computed initial priorities
        (reference replay.py:59-69 ``add(priorities, xp_batch)``).

        Overwrites the oldest slots when full (FIFO).  Returns the slot
        indices written.
        """
        priorities = np.asarray(priorities, dtype=np.float64)
        n = priorities.shape[0]
        if n == 0:
            return np.zeros((0,), np.int64)
        if n > self.capacity:
            raise ValueError(f"batch of {n} exceeds capacity {self.capacity}")
        # Per-frame encode work (deflate, for the compressed store) happens
        # OFF the lock — an 8k-row actor flush must not stall the learner's
        # sample() for its compression time.
        enc_obs = self._obs.encode(batch.obs)
        enc_next_obs = self._next_obs.encode(batch.next_obs)
        with self._lock:
            idx = (self._cursor + np.arange(n)) % self.capacity
            self._obs.put_encoded(idx, enc_obs)
            self._next_obs.put_encoded(idx, enc_next_obs)
            self._action[idx] = batch.action
            self._reward[idx] = batch.reward
            self._discount[idx] = batch.discount
            self._tree.set(idx, np.power(np.maximum(priorities, 1e-12), self.alpha))
            self._cursor = int((self._cursor + n) % self.capacity)
            self._count += n
            return idx

    # -- read path (learner) --------------------------------------------

    def sample(
        self,
        batch_size: int,
        beta: float = 0.4,
        rng: Optional[np.random.Generator] = None,
    ) -> PrioritizedBatch:
        """Stratified proportional sample with IS weights.

        P(i) = p_i^α / Σ p^α;  w_i = (N · P(i))^−β, normalized by max w
        (the standard PER correction the reference lists as TODO, β from
        parameters.json:30).
        """
        rng = rng or np.random.default_rng()
        with self._lock:
            size = min(self._count, self.capacity)
            if size == 0:
                raise ValueError("cannot sample from an empty replay")
            idx = self._tree.sample_stratified(batch_size, rng)
            mass = self._tree.get(idx)
            total = self._tree.total
            transition = NStepTransition(
                obs=self._obs.get(idx),
                action=self._action[idx].copy(),
                reward=self._reward[idx].copy(),
                discount=self._discount[idx].copy(),
                next_obs=self._next_obs.get(idx),
            )
        probs = mass / total
        weights = np.power(size * np.maximum(probs, 1e-12), -beta)
        weights = (weights / weights.max()).astype(np.float32)
        return PrioritizedBatch(
            transition=transition,
            indices=idx.astype(np.int32),
            is_weights=weights,
        )

    def sample_with_mass(
        self,
        batch_size: int,
        rng: Optional[np.random.Generator] = None,
    ) -> tuple:
        """(transition, indices, mass, total_mass, size) — the raw
        proportional sample WITHOUT the IS-weight arithmetic, for callers
        that normalize globally (the replay service's sharded sample:
        each shard returns its slots' p^α masses and its own total, the
        learner-side client folds every shard's total into the global
        denominator — replay/service.py)."""
        rng = rng or np.random.default_rng()
        with self._lock:
            size = min(self._count, self.capacity)
            if size == 0:
                raise ValueError("cannot sample from an empty replay")
            idx = self._tree.sample_stratified(batch_size, rng)
            mass = self._tree.get(idx)
            total = self._tree.total
            transition = NStepTransition(
                obs=self._obs.get(idx),
                action=self._action[idx].copy(),
                reward=self._reward[idx].copy(),
                discount=self._discount[idx].copy(),
                next_obs=self._next_obs.get(idx),
            )
        return transition, idx.astype(np.int64), mass, float(total), size

    def update_priorities(self, indices: np.ndarray, priorities: np.ndarray) -> None:
        """Learner priority feedback (reference ``set_priorities``,
        replay.py:32 — here per-transition and O(B log N)).

        If a sampled slot was recycled between sample and update, the fresh
        transition briefly carries the old transition's updated priority —
        a benign, self-correcting race (the slot is resampled and restamped
        within a few steps), and the same whole-value-atomicity discipline
        the reference relies on (SURVEY §5 race detection).
        """
        indices = np.asarray(indices, dtype=np.int64)
        priorities = np.asarray(priorities, dtype=np.float64)
        if indices.size == 0:
            return
        with self._lock:
            self._tree.set(
                indices, np.power(np.maximum(priorities, 1e-12), self.alpha)
            )
            self._track_dirty_locked(indices)

    def _track_dirty_locked(self, indices: np.ndarray) -> None:
        if self._ckpt is None:
            return
        self._dirty.append(np.array(indices, np.int64, copy=True))
        self._dirty_rows += len(indices)
        if self._dirty_rows > 4 * self.capacity:
            # Overflow guard: the sparse record would rival a full
            # snapshot — drop tracking, the next delta becomes a base.
            self._dirty, self._dirty_rows, self._ckpt = [], 0, None

    # -- cold tier surface (replay/tiered.py; no-ops when tier is off) ---

    @property
    def tier(self):
        return getattr(self._obs, "ring", None)

    def tier_over_watermark(self) -> bool:
        ring = getattr(self._obs, "ring", None)
        if ring is None:
            return False
        return (ring.over_high_watermark()
                or self._next_obs.ring.over_high_watermark())

    def spill_cold(self, max_spans: int = 0) -> tuple:
        """Evict least-recently-sampled spans in both stores down to their
        low watermarks (TierEvictor's entry point)."""
        if getattr(self._obs, "ring", None) is None:
            return 0, 0
        with self._lock:
            s1, b1 = self._obs.ring.spill(max_spans=max_spans)
            s2, b2 = self._next_obs.ring.spill(max_spans=max_spans)
            return s1 + s2, b1 + b2

    def tier_stats(self) -> Optional[dict]:
        ring = getattr(self._obs, "ring", None)
        if ring is None:
            return None
        with self._lock:
            a, b = ring.tier_stats(), self._next_obs.ring.tier_stats()
        out = {}
        for k in a:
            if k == "fault_ms":
                out[k] = a[k] if a[k]["count"] else b[k]
            elif k == "span_frames":
                out[k] = a[k]
            else:
                out[k] = a[k] + b[k]
        return out

    # -- misc ------------------------------------------------------------

    def size(self) -> int:
        """Current number of stored transitions (reference replay.py:82)."""
        with self._lock:
            return min(self._count, self.capacity)

    @property
    def total_added(self) -> int:
        return self._count

    def frames_nbytes(self) -> int:
        """Bytes held by frame storage (compressed stores report the
        deflated size — the observable for the memory win)."""
        with self._lock:
            return self._obs.nbytes() + self._next_obs.nbytes()

    def max_priority(self) -> float:
        with self._lock:
            m = self._tree.max_priority()
        return float(m ** (1.0 / self.alpha)) if m > 0 else 1.0

    def digest(self, with_crc: bool = True) -> dict:
        """Content fingerprint for bit-exact recovery proofs (the replay
        service's ``state_digest`` RPC): counters, total p^α mass, and —
        with ``with_crc`` — a crc32 over every live column including the
        materialized frames.  The crc is an O(size) scan (the cheap
        counter-only form is what liveness probes use); two replays with
        equal digests hold bit-identical sampleable state."""
        import struct as _struct

        with self._lock:
            size = min(self._count, self.capacity)
            out = {
                "count": int(self._count),
                "cursor": int(self._cursor),
                "size": int(size),
                "total_mass": float(self._tree.total),
                "crc": 0,
            }
            if not with_crc:
                return out
            idx = np.arange(size)
            c = zlib.crc32(_struct.pack("<qq", self._count, self._cursor))
            for arr in (
                self._action[:size], self._reward[:size],
                self._discount[:size], self._tree.get(idx),
                self._obs.get(idx), self._next_obs.get(idx),
            ):
                c = zlib.crc32(np.ascontiguousarray(arr).tobytes(), c)
            out["crc"] = int(c)
            return out

    # -- snapshot (checkpointing) ----------------------------------------

    def state_dict(self) -> dict:
        """Snapshot for checkpoint/resume (the reference checkpoints nothing
        of the replay — SURVEY §5 checkpoint/resume)."""
        with self._lock:
            return self._state_dict_locked()

    def _state_dict_locked(self) -> dict:
        size = min(self._count, self.capacity)
        idx = np.arange(size)
        out = {
            "action": self._action[:size].copy(),
            "reward": self._reward[:size].copy(),
            "discount": self._discount[:size].copy(),
            "tree_priorities": self._tree.get(idx),
            "cursor": self._cursor,
            "count": self._count,
        }
        if self._obs.compressed:
            # Snapshot the deflated slots verbatim: a 2M-slot compressed
            # buffer must never materialize its ~28 GB dense form just
            # to checkpoint (that's why compression was configured).
            out["obs_blob"], out["obs_lens"] = self._obs.export_blobs(size)
            out["next_obs_blob"], out["next_obs_lens"] = (
                self._next_obs.export_blobs(size)
            )
        else:
            out["obs"] = self._obs.get(idx)
            out["next_obs"] = self._next_obs.get(idx)
        return out

    # -- incremental snapshot (utils/checkpoint_inc delta protocol) -------

    def delta_state_dict(self, force_base: bool = False) -> dict:
        """A full base (first call / forced / overrun) or the dirty-span
        delta since the previous call: the ring span written since the last
        snapshot plus the sparse restamped priorities — bytes ∝ checkpoint
        interval, not capacity.  Resets the dirty mark."""
        with self._lock:
            n_new = self._count - (self._ckpt[0] if self._ckpt else 0)
            if force_base or self._ckpt is None or n_new >= self.capacity:
                out = self._state_dict_locked()
                out["chain_mark"] = np.asarray([self._count], np.int64)
                self._mark_locked()
                return out
            prev_count, prev_cursor = self._ckpt
            span = (prev_cursor + np.arange(n_new)) % self.capacity
            dirty = self._drain_dirty_locked()
            out = {
                "delta": np.asarray(True),
                "chain_prev": np.asarray([prev_count], np.int64),
                "chain_mark": np.asarray([self._count], np.int64),
                "span_idx": span,
                "span_action": self._action[span].copy(),
                "span_reward": self._reward[span].copy(),
                "span_discount": self._discount[span].copy(),
                "span_tree": self._tree.get(span),
                "prio_idx": dirty,
                "prio_mass": self._tree.get(dirty),
                "cursor": self._cursor,
                "count": self._count,
            }
            if self._obs.compressed:
                out["span_obs_blob"], out["span_obs_lens"] = (
                    self._obs.export_blobs_idx(span)
                )
                out["span_next_obs_blob"], out["span_next_obs_lens"] = (
                    self._next_obs.export_blobs_idx(span)
                )
            else:
                out["span_obs"] = self._obs.get(span)
                out["span_next_obs"] = self._next_obs.get(span)
            self._mark_locked()
            return out

    def _mark_locked(self) -> None:
        self._ckpt = (self._count, self._cursor)
        self._dirty, self._dirty_rows = [], 0

    def _drain_dirty_locked(self) -> np.ndarray:
        if not self._dirty:
            return np.zeros((0,), np.int64)
        idx = np.unique(np.concatenate(self._dirty))
        return idx[(idx >= 0) & (idx < self.capacity)]

    def apply_delta_state_dict(self, delta: dict) -> None:
        """Restore-side replay of one delta (chained onto the current
        counters — a discontinuity raises instead of silently composing)."""
        with self._lock:
            if "delta" not in delta:
                raise ValueError("not a delta snapshot (missing 'delta' key)")
            if int(np.asarray(delta["chain_prev"]).reshape(-1)[0]) != self._count:
                raise ValueError(
                    f"delta chain discontinuity: delta continues count "
                    f"{int(np.asarray(delta['chain_prev']).reshape(-1)[0])}, "
                    f"replay is at {self._count}"
                )
            span = np.asarray(delta["span_idx"], np.int64)
            if "span_obs_blob" in delta:
                if not self._obs.compressed:
                    raise ValueError(
                        "compressed-span delta into a raw frame store — "
                        "replay.frame_compression must match across resume"
                    )
                self._obs.import_blobs_idx(
                    span, delta["span_obs_blob"], delta["span_obs_lens"]
                )
                self._next_obs.import_blobs_idx(
                    span, delta["span_next_obs_blob"],
                    delta["span_next_obs_lens"],
                )
            else:
                if self._obs.compressed:
                    raise ValueError(
                        "raw-span delta into a compressed frame store — "
                        "replay.frame_compression must match across resume"
                    )
                self._obs.put(span, delta["span_obs"])
                self._next_obs.put(span, delta["span_next_obs"])
            self._action[span] = delta["span_action"]
            self._reward[span] = delta["span_reward"]
            self._discount[span] = delta["span_discount"]
            self._tree.set(span, np.asarray(delta["span_tree"], np.float64))
            prio_idx = np.asarray(delta["prio_idx"], np.int64)
            if prio_idx.size:
                self._tree.set(
                    prio_idx, np.asarray(delta["prio_mass"], np.float64)
                )
            self._cursor = int(delta["cursor"]) % self.capacity
            self._count = int(delta["count"])
            self._mark_locked()

    def load_state_dict(self, state: dict) -> None:
        compressed_snap = "obs_blob" in state
        with self._lock:
            size = (
                state["obs_lens"].shape[0] if compressed_snap
                else state["obs"].shape[0]
            )
            if size > self.capacity:
                raise ValueError("snapshot larger than capacity")
            # Clear everything first so a restore into a warm buffer cannot
            # leave stale transitions sampleable past the snapshot region.
            self._tree.set(
                np.arange(self.capacity), np.zeros(self.capacity, np.float64)
            )
            rng = np.arange(size)
            if compressed_snap and self._obs.compressed:
                self._obs.import_blobs(state["obs_blob"], state["obs_lens"])
                self._next_obs.import_blobs(
                    state["next_obs_blob"], state["next_obs_lens"]
                )
            elif compressed_snap:
                # Cross-restore into a raw store: inflate through a scratch
                # compressed view.
                tmp = CompressedFrameStore(size, self._obs.shape,
                                           self._obs.dtype)
                tmp.import_blobs(state["obs_blob"], state["obs_lens"])
                self._obs.put(rng, tmp.get(rng))
                tmp.import_blobs(state["next_obs_blob"], state["next_obs_lens"])
                self._next_obs.put(rng, tmp.get(rng))
            else:
                self._obs.put(rng, state["obs"])
                self._next_obs.put(rng, state["next_obs"])
            self._action[:size] = state["action"]
            self._reward[:size] = state["reward"]
            self._discount[:size] = state["discount"]
            self._tree.set(np.arange(size), state["tree_priorities"])
            self._cursor = int(state["cursor"]) % self.capacity
            self._count = int(state["count"])
            # A full load invalidates any dirty-span tracking; the next
            # incremental save emits a base unless deltas follow (the
            # checkpoint_inc restore applies them, re-establishing the
            # mark via apply_delta_state_dict).
            self._ckpt, self._dirty, self._dirty_rows = None, [], 0
