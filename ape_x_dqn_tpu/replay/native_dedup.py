"""ctypes bindings for the native frame-dedup replay core — the
paper-scale host path (round-4 verdict item 1b).

``NativeDedupReplay`` is a drop-in for ``replay.dedup.DedupReplay`` (same
constructor surface + add/sample/update_priorities/size/state_dict), with
every learner-facing operation fused into ONE GIL-released C call
(_native/replay_core.cc): tree descent + IS weights + both frame gathers
in ``rc_sample``; ring writes + priority set + liveness sweep in
``rc_add``.  The sum-tree is striped ``n_stripes`` ways with per-stripe
locks; the striped sampling law matches the sharded device replay's
(equal rows per stripe, IS-corrected) so runs can move between host
stripes and device shards without changing the estimator.  At
``n_stripes > 1`` sample/update fan out as one GIL-released C call PER
STRIPE (``rc_sample_stripe`` / ``rc_update_stripe``) through a
persistent thread pool, so stripe work genuinely overlaps in wall-clock
on multicore hosts — the BENCH_r06 "striped4 wrapper serializes calls"
defect, fixed; tests assert the overlap and bit-parity with the serial
spelling.  Ingest (``add``) still serializes under the wrapper lock
(carry-resolver state is Python-side).  ``n_stripes=1`` is bit-exact
with the numpy twin (tests/test_native_dedup.py pins it).

Build discipline mirrors replay/native.py: compile on first use with g++,
atomic rename, cached .so keyed by source mtime; ``native_dedup_available``
gates callers to the numpy fallback when the toolchain is missing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from typing import Optional

import numpy as np

from ape_x_dqn_tpu.replay.dedup import CarryResolver
from ape_x_dqn_tpu.types import DedupChunk, NStepTransition, PrioritizedBatch

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_HERE, "_native", "replay_core.cc")
_SO = os.path.join(_HERE, "_native", "replay_core.so")

_lib = None
_lib_err: str | None = None
_lock = threading.Lock()

_i64p = ctypes.POINTER(ctypes.c_int64)
_i32p = ctypes.POINTER(ctypes.c_int32)
_f32p = ctypes.POINTER(ctypes.c_float)
_f64p = ctypes.POINTER(ctypes.c_double)
_u8p = ctypes.POINTER(ctypes.c_uint8)


def _build() -> None:
    tmp = f"{_SO}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.rename(tmp, _SO)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load():
    global _lib, _lib_err
    with _lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
            lib.rc_create.restype = ctypes.c_void_p
            lib.rc_create.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_double, ctypes.c_int32,
            ]
            lib.rc_destroy.argtypes = [ctypes.c_void_p]
            for name in ("rc_size", "rc_count", "rc_fcount", "rc_cursor",
                         "rc_frame_dead"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int64
                fn.argtypes = [ctypes.c_void_p]
            for name in ("rc_total", "rc_max"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_double
                fn.argtypes = [ctypes.c_void_p]
            lib.rc_get_mass.restype = ctypes.c_double
            lib.rc_get_mass.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.rc_add.restype = ctypes.c_int64
            lib.rc_add.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, _u8p, ctypes.c_int64,
                _i64p, _i64p, _i32p, _f32p, _f32p, _f32p,
            ]
            lib.rc_sample.restype = ctypes.c_int32
            lib.rc_sample.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_double, _f64p,
                _i64p, _f64p, _u8p, _u8p, _i32p, _f32p, _f32p,
            ]
            lib.rc_sample_stripe.restype = ctypes.c_int32
            lib.rc_sample_stripe.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64,
                ctypes.c_double, _f64p,
                _i64p, _f64p, _u8p, _u8p, _i32p, _f32p, _f32p,
            ]
            lib.rc_update.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, _i64p, _f32p,
            ]
            lib.rc_update_stripe.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64,
                _i64p, _f32p,
            ]
            lib.rc_export.argtypes = [
                ctypes.c_void_p, _u8p, _i64p, _i64p, _i32p, _f32p, _f32p,
                _u8p, _f64p,
            ]
            lib.rc_import.restype = ctypes.c_int32
            lib.rc_import.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, _u8p, ctypes.c_int64,
                _i64p, _i64p, _i32p, _f32p, _f32p, _u8p, _f64p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ]
            # Incremental-snapshot surface (dirty spans + sparse).
            lib.rc_export_alive.argtypes = [ctypes.c_void_p, _u8p]
            lib.rc_export_frames_span.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, _u8p,
            ]
            lib.rc_import_frames_span.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, _u8p,
            ]
            lib.rc_export_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                _i64p, _i64p, _i32p, _f32p, _f32p, _u8p, _f64p,
            ]
            lib.rc_import_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                _i64p, _i64p, _i32p, _f32p, _f32p, _u8p, _f64p,
            ]
            lib.rc_export_mass.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, _i64p, _f64p,
            ]
            lib.rc_apply_sparse.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, _i64p, _u8p, _f64p,
            ]
            lib.rc_set_counters.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64,
            ]
            # Tiered frame store surface (replay/tiered.SpanTierIndex):
            # evict/fault move span bytes without the GIL; the two-phase
            # sample splits descent from the frame gathers so cold spans
            # can fault in between.
            lib.rc_evict_span.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, _u8p,
            ]
            lib.rc_fault_span.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, _u8p,
            ]
            lib.rc_sample_idx.restype = ctypes.c_int32
            lib.rc_sample_idx.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_double, _f64p,
                _i64p, _f64p, _i64p, _i64p, _i32p, _f32p, _f32p,
            ]
            lib.rc_gather_frames.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, _i64p, _u8p, _u8p,
            ]
            lib.rc_drop_span.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ]
            lib.rc_nohugepage.argtypes = [ctypes.c_void_p]
            lib.rc_fault_batch.restype = ctypes.c_int64
            lib.rc_fault_batch.argtypes = [
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64,
                _i64p, _i64p, _i64p, _i64p, _i64p,
            ]
            _lib = lib
        except Exception as e:  # compiler missing, build/load failure
            _lib_err = f"{type(e).__name__}: {e}"
        return _lib


def native_dedup_available() -> bool:
    return _load() is not None


def native_dedup_error() -> str | None:
    _load()
    return _lib_err


def _p(a: np.ndarray, ptr_t):
    return a.ctypes.data_as(ptr_t)


class NativeDedupReplay:
    """C++-core frame-dedup prioritized replay (interface of DedupReplay)."""

    def __init__(
        self,
        capacity: int,
        obs_shape,
        priority_exponent: float = 0.6,
        obs_dtype=np.uint8,
        frame_ratio: float = 1.25,
        n_stripes: int = 1,
        hot_frame_budget_bytes: int = 0,
        spill_dir: Optional[str] = None,
        spill_span_frames: int = 0,
        spill_watermark_high: float = 1.0,
        spill_watermark_low: float = 0.9,
    ):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native replay core unavailable: {_lib_err}")
        if np.dtype(obs_dtype) != np.uint8:
            raise ValueError("native dedup core stores uint8 frames")
        self._lib = lib
        self.capacity = int(capacity)
        self.frame_capacity = max(1, int(round(capacity * frame_ratio)))
        self.obs_shape = tuple(obs_shape)
        self.frame_bytes = int(np.prod(self.obs_shape))
        self.alpha = float(priority_exponent)
        self.n_stripes = int(n_stripes)
        self._handle = lib.rc_create(
            self.capacity, self.frame_capacity, self.frame_bytes,
            self.alpha, self.n_stripes,
        )
        if not self._handle:
            raise MemoryError("rc_create failed")
        self._resolver = CarryResolver()
        self._lock = threading.Lock()
        # Tiered frame store (replay/tiered.py): the C mmap stays the
        # address-stable hot storage; SpanTierIndex decides which spans are
        # resident, spilling least-recently-sampled ones through
        # rc_evict_span (copy out + MADV_DONTNEED — RSS actually drops)
        # and faulting them back through rc_fault_span, all GIL-released.
        # Sampling switches to the two-phase rc_sample_idx +
        # rc_gather_frames so the needed spans fault between descent and
        # gather; off (the default) every call below is byte-identical to
        # the untiered build — zero cost when disabled.
        self._tier = None
        if hot_frame_budget_bytes > 0:
            from ape_x_dqn_tpu.replay.tiered import SpanTierIndex

            if spill_dir is None:
                raise ValueError("tiered replay needs a spill_dir")
            # THP off for tiered rings: span drops would split 2 MB pages
            # on every eviction (see rc_nohugepage).
            lib.rc_nohugepage(self._handle)
            self._tier = SpanTierIndex(
                self.frame_capacity, self.obs_shape, np.uint8,
                hot_budget_bytes=hot_frame_budget_bytes,
                spill_path=os.path.join(spill_dir, "frames.cold"),
                read_fn=self._tier_read_span,
                evict_fn=self._tier_evict_span,
                fault_fn=self._tier_fault_span,
                fault_batch_fn=self._tier_fault_batch,
                drop_fn=self._tier_drop_span,
                span_frames=spill_span_frames,
                watermark_high=spill_watermark_high,
                watermark_low=spill_watermark_low,
            )
        # Persistent per-stripe fan-out pool (n_stripes > 1): one
        # GIL-released C call per stripe, dispatched concurrently — see
        # _sample_with_uniforms / update_priorities.  Lazy would race the
        # first sample; built here, it costs n idle threads.
        self._pool = None
        if self.n_stripes > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=self.n_stripes,
                thread_name_prefix="dedup-stripe",
            )
        # (t_start, t_end) wall-clock spans of the last fan-out's stripe
        # calls — the concurrency test asserts they overlap.
        self.last_stripe_spans: list = []
        # Incremental-checkpoint dirty tracking (utils/checkpoint_inc):
        # (count, cursor, fcount, alive copy) at the last snapshot; the
        # liveness sweep runs inside rc_add, so swept slots are recovered
        # by diffing the alive vector instead of recording indices.
        self._ckpt = None
        self._dirty: list = []
        self._dirty_rows = 0

    def __del__(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            pool.shutdown(wait=False)
        tier = getattr(self, "_tier", None)
        if tier is not None:
            tier.close()
        h = getattr(self, "_handle", None)
        if h:
            self._lib.rc_destroy(h)
            self._handle = None

    # -- cold tier plumbing (SpanTierIndex callables + public surface) ----

    def _tier_read_span(self, start: int, n: int) -> np.ndarray:
        out = np.empty((n, *self.obs_shape), np.uint8)
        self._lib.rc_export_frames_span(self._handle, int(start), int(n),
                                        _p(out, _u8p))
        return out

    def _tier_evict_span(self, start: int, n: int) -> np.ndarray:
        out = np.empty((n, *self.obs_shape), np.uint8)
        self._lib.rc_evict_span(self._handle, int(start), int(n),
                                _p(out, _u8p))
        return out

    def _tier_fault_span(self, start: int, n: int, frames) -> None:
        blk = np.ascontiguousarray(frames, np.uint8)
        self._lib.rc_fault_span(self._handle, int(start), int(n),
                                _p(blk, _u8p))

    def _tier_drop_span(self, start: int, n: int) -> None:
        self._lib.rc_drop_span(self._handle, int(start), int(n))

    def _tier_fault_batch(self, fd, offsets, fstarts, lens, sids,
                          want_crcs) -> int:
        return int(self._lib.rc_fault_batch(
            self._handle, int(fd), offsets.shape[0],
            _p(offsets, _i64p), _p(fstarts, _i64p), _p(lens, _i64p),
            _p(sids, _i64p), _p(want_crcs, _i64p),
        ))

    @property
    def tier(self):
        return self._tier

    def tier_over_watermark(self) -> bool:
        return self._tier is not None and self._tier.over_high_watermark()

    def spill_cold(self, max_spans: int = 0, target_bytes=None) -> tuple:
        if self._tier is None:
            return 0, 0
        with self._lock:
            return self._tier.spill(max_spans=max_spans,
                                    target_bytes=target_bytes)

    def tier_flush_dirty(self) -> int:
        """Write-back every dirty hot span's cold record (residency kept)
        under the replay lock — pre-trim/pre-bench hygiene."""
        if self._tier is None:
            return 0
        with self._lock:
            return self._tier.flush_dirty()

    def tier_stats(self) -> Optional[dict]:
        if self._tier is None:
            return None
        with self._lock:
            return self._tier.tier_stats()

    def _ensure_hot_all_locked(self) -> None:
        """Materialize the full written frame region (public full
        snapshots and legacy whole-ring exports)."""
        nf = min(int(self._lib.rc_fcount(self._handle)),
                 self.frame_capacity)
        if nf:
            self._tier.ensure_hot(self._tier.spans_of_run(0, nf))

    # -- write path ------------------------------------------------------

    def add(self, priorities: np.ndarray, chunk: DedupChunk) -> np.ndarray:
        prio = np.ascontiguousarray(priorities, np.float32)
        frames = np.ascontiguousarray(chunk.frames, np.uint8)
        U, M = frames.shape[0], prio.shape[0]
        if M > self.capacity or U > self.frame_capacity:
            raise ValueError("chunk exceeds ring capacity")
        with self._lock:
            base = int(self._lib.rc_fcount(self._handle))
            if self._tier is not None:
                # Cold spans the write only PARTIALLY covers fault first
                # (rc_add memcpys into the mmap; a dropped span's other
                # slots live only in the cold record).
                self._tier.note_write(base % self.frame_capacity, U)
            obs_seq, next_seq, keep = self._resolver.resolve(chunk, base)
            obs_seq = np.ascontiguousarray(obs_seq[keep])
            next_seq = np.ascontiguousarray(next_seq[keep])
            action = np.ascontiguousarray(chunk.action, np.int32)[keep]
            reward = np.ascontiguousarray(chunk.reward, np.float32)[keep]
            discount = np.ascontiguousarray(chunk.discount, np.float32)[keep]
            pk = np.ascontiguousarray(prio[keep])
            m = obs_seq.shape[0]
            first = self._lib.rc_add(
                self._handle, U, _p(frames, _u8p), m,
                _p(obs_seq, _i64p), _p(next_seq, _i64p),
                _p(action, _i32p), _p(reward, _f32p),
                _p(discount, _f32p), _p(pk, _f32p),
            )
            if first < 0:
                raise ValueError("rc_add rejected the chunk (size violation)")
            return (first + np.arange(m, dtype=np.int64)) % self.capacity

    # -- read path -------------------------------------------------------

    def sample(
        self,
        batch_size: int,
        beta: float = 0.4,
        rng: Optional[np.random.Generator] = None,
    ) -> PrioritizedBatch:
        rng = rng or np.random.default_rng()
        u = np.ascontiguousarray(rng.random(int(batch_size)))
        return self._sample_with_uniforms(u, beta)

    def _sample_with_uniforms(self, u: np.ndarray,
                              beta: float) -> PrioritizedBatch:
        """Sample with caller-supplied uniforms (RNG stays in Python so
        the numpy twin is a bit-exact oracle; tests also inject uniforms
        to pin the parallel fan-out against the serial C spelling).

        n_stripes == 1 takes the single fused ``rc_sample`` call (the
        oracle path); n_stripes > 1 fans one ``rc_sample_stripe`` call
        per stripe out through the persistent pool — each call releases
        the GIL, descends only its own tree, and gathers its own rows
        into disjoint slices of the output buffers, so the stripes run
        concurrently in wall-clock.  Raw per-stripe weights are
        normalized here by the global max, reproducing ``rc_sample``'s
        arithmetic bit-for-bit.
        """
        B = int(u.shape[0])
        idx = np.empty(B, np.int64)
        weights = np.empty(B, np.float64)
        obs = np.empty((B, *self.obs_shape), np.uint8)
        next_obs = np.empty((B, *self.obs_shape), np.uint8)
        action = np.empty(B, np.int32)
        reward = np.empty(B, np.float32)
        discount = np.empty(B, np.float32)
        if B % self.n_stripes:
            raise ValueError(
                f"batch_size {B} must divide by n_stripes {self.n_stripes}"
            )
        with self._lock:
            if self._tier is not None:
                # Two-phase tiered sample: descend + weights + metadata in
                # one GIL-released call (bit-identical law to rc_sample,
                # stripes included), fault the spans this batch actually
                # references, then gather.  The stripe fan-out pool is
                # bypassed — the fault step is inherently serial.
                obs_seq = np.empty(B, np.int64)
                next_seq = np.empty(B, np.int64)
                rc = self._lib.rc_sample_idx(
                    self._handle, B, float(beta), _p(u, _f64p),
                    _p(idx, _i64p), _p(weights, _f64p),
                    _p(obs_seq, _i64p), _p(next_seq, _i64p),
                    _p(action, _i32p), _p(reward, _f32p),
                    _p(discount, _f32p),
                )
                if rc == -1:
                    raise ValueError("cannot sample from an empty replay")
                slots = np.concatenate([obs_seq, next_seq]) \
                    % self.frame_capacity
                self._tier.ensure_hot(self._tier.spans_of_slots(slots))
                self._lib.rc_gather_frames(
                    self._handle, B, _p(idx, _i64p),
                    _p(obs, _u8p), _p(next_obs, _u8p),
                )
            elif self.n_stripes == 1:
                rc = self._lib.rc_sample(
                    self._handle, B, float(beta), _p(u, _f64p),
                    _p(idx, _i64p), _p(weights, _f64p), _p(obs, _u8p),
                    _p(next_obs, _u8p), _p(action, _i32p),
                    _p(reward, _f32p), _p(discount, _f32p),
                )
                if rc == -1:
                    raise ValueError("cannot sample from an empty replay")
            else:
                Bk = B // self.n_stripes

                def one(s: int):
                    sl = slice(s * Bk, (s + 1) * Bk)
                    t0 = time.monotonic()
                    rc = self._lib.rc_sample_stripe(
                        self._handle, s, Bk, float(beta),
                        _p(u[sl], _f64p), _p(idx[sl], _i64p),
                        _p(weights[sl], _f64p), _p(obs[sl], _u8p),
                        _p(next_obs[sl], _u8p), _p(action[sl], _i32p),
                        _p(reward[sl], _f32p), _p(discount[sl], _f32p),
                    )
                    return rc, (t0, time.monotonic())

                futs = [
                    self._pool.submit(one, s)
                    for s in range(self.n_stripes)
                ]
                results = [f.result() for f in futs]
                self.last_stripe_spans = [span for _, span in results]
                if any(rc == -1 for rc, _ in results):
                    raise ValueError("cannot sample from an empty replay")
                weights /= weights.max()
        return PrioritizedBatch(
            transition=NStepTransition(
                obs=obs, action=action, reward=reward,
                discount=discount, next_obs=next_obs,
            ),
            indices=idx.astype(np.int32),
            is_weights=weights.astype(np.float32),
        )

    def update_priorities(self, indices, priorities) -> None:
        idx = np.ascontiguousarray(indices, np.int64)
        prio = np.ascontiguousarray(priorities, np.float32)
        if idx.size == 0:
            return
        with self._lock:
            if self.n_stripes == 1:
                self._lib.rc_update(
                    self._handle, idx.shape[0], _p(idx, _i64p),
                    _p(prio, _f32p)
                )
            else:
                # Fan-out: each stripe worker scans the batch and applies
                # only its own slots — no cross-stripe lock contention,
                # in-order last-write-wins preserved within each stripe
                # (slot -> stripe is a partition, so across-stripe order
                # cannot matter).
                futs = [
                    self._pool.submit(
                        self._lib.rc_update_stripe, self._handle, s,
                        idx.shape[0], _p(idx, _i64p), _p(prio, _f32p),
                    )
                    for s in range(self.n_stripes)
                ]
                for f in futs:
                    f.result()
            if self._ckpt is not None:
                self._dirty.append(idx.copy())
                self._dirty_rows += idx.shape[0]
                if self._dirty_rows > 4 * self.capacity:
                    # Sparse record rivals a base — retrack from scratch.
                    self._dirty, self._dirty_rows = [], 0
                    self._ckpt = None

    # -- misc ------------------------------------------------------------

    def size(self) -> int:
        return int(self._lib.rc_size(self._handle))

    @property
    def total_added(self) -> int:
        return int(self._lib.rc_count(self._handle))

    @property
    def stats(self) -> dict:
        return {
            "frame_dead": int(self._lib.rc_frame_dead(self._handle)),
            "dropped_carry": self._resolver.dropped_carry,
        }

    def frames_nbytes(self) -> int:
        return self.frame_capacity * self.frame_bytes

    def max_priority(self) -> float:
        m = float(self._lib.rc_max(self._handle))
        return float(m ** (1.0 / self.alpha)) if m > 0 else 1.0

    # -- snapshot --------------------------------------------------------

    def state_dict(self) -> dict:
        with self._lock:
            return self._state_dict_locked()

    def _state_dict_locked(self, cold_refs: bool = False) -> dict:
        size = self.size()
        nf = min(int(self._lib.rc_fcount(self._handle)),
                 self.frame_capacity)
        # Frame leg first: cold_refs=True on a tiered ring references cold
        # spans by (offset, len, crc) into the spill file — a mostly-cold
        # base must not page the whole ring back in just to checkpoint.
        refs = None
        if cold_refs and self._tier is not None:
            refs = self._tier.cold_refs(nf)
        if refs is None:
            if self._tier is not None:
                self._ensure_hot_all_locked()
            frames = np.empty((nf, *self.obs_shape), np.uint8)
            frames_p = _p(frames, _u8p)
        else:
            frames = None
            # rc_export still wants a destination; rows come from
            # rc_export_rows below instead, so skip it entirely.
        obs_seq = np.empty(size, np.int64)
        next_seq = np.empty(size, np.int64)
        action = np.empty(size, np.int32)
        reward = np.empty(size, np.float32)
        discount = np.empty(size, np.float32)
        alive = np.empty(size, np.uint8)
        mass = np.empty(size, np.float64)
        if refs is None:
            self._lib.rc_export(
                self._handle, frames_p, _p(obs_seq, _i64p),
                _p(next_seq, _i64p), _p(action, _i32p), _p(reward, _f32p),
                _p(discount, _f32p), _p(alive, _u8p), _p(mass, _f64p),
            )
        else:
            self._lib.rc_export_rows(
                self._handle, 0, size, _p(obs_seq, _i64p),
                _p(next_seq, _i64p), _p(action, _i32p), _p(reward, _f32p),
                _p(discount, _f32p), _p(alive, _u8p), _p(mass, _f64p),
            )
        src_ids, src_state = self._resolver.state_arrays()
        out = {
            "dedup": np.asarray(True),
            "obs_seq": obs_seq, "next_seq": next_seq,
            "action": action, "reward": reward, "discount": discount,
            "alive": alive.astype(bool),
            "tree_priorities": mass,
            "cursor": int(self._lib.rc_cursor(self._handle)),
            "count": self.total_added,
            "fcount": int(self._lib.rc_fcount(self._handle)),
            "frame_dead": int(self._lib.rc_frame_dead(self._handle)),
            "dropped_carry": self._resolver.dropped_carry,
            "frame_capacity": self.frame_capacity,
            "src_ids": src_ids, "src_state": src_state,
        }
        if refs is None:
            out["frames"] = frames
        else:
            out.update(refs)
        return out

    # -- incremental snapshot (utils/checkpoint_inc delta protocol) -------
    # Dict format is IDENTICAL to DedupReplay's delta — chains written by
    # either implementation restore into the other (the numpy twin stays
    # the native core's oracle all the way through checkpointing).

    def delta_state_dict(self, force_base: bool = False) -> dict:
        with self._lock:
            count = self.total_added
            fcount = int(self._lib.rc_fcount(self._handle))
            cursor = int(self._lib.rc_cursor(self._handle))
            prev = self._ckpt
            n_new = count - (prev[0] if prev else 0)
            f_new = fcount - (prev[2] if prev else 0)
            if (force_base or prev is None or n_new >= self.capacity
                    or f_new >= self.frame_capacity):
                out = self._state_dict_locked(cold_refs=True)
                out["chain_mark"] = np.asarray([count, fcount], np.int64)
                self._mark_locked(count, cursor, fcount)
                return out
            prev_count, prev_cursor, prev_fcount, alive_mark = prev
            span = (prev_cursor + np.arange(n_new)) % self.capacity
            obs_seq = np.empty(n_new, np.int64)
            next_seq = np.empty(n_new, np.int64)
            action = np.empty(n_new, np.int32)
            reward = np.empty(n_new, np.float32)
            discount = np.empty(n_new, np.float32)
            alive = np.empty(n_new, np.uint8)
            mass = np.empty(n_new, np.float64)
            self._lib.rc_export_rows(
                self._handle, prev_cursor, n_new, _p(obs_seq, _i64p),
                _p(next_seq, _i64p), _p(action, _i32p), _p(reward, _f32p),
                _p(discount, _f32p), _p(alive, _u8p), _p(mass, _f64p),
            )
            fspan = (prev_fcount + np.arange(f_new)) % self.frame_capacity
            frames = np.empty((f_new, *self.obs_shape), np.uint8)
            if self._tier is not None and f_new:
                # The freshly written span may already have been evicted
                # (tiny hot budgets) — fault it for the export.
                self._tier.ensure_hot(self._tier.spans_of_run(
                    prev_fcount % self.frame_capacity, f_new
                ))
            self._lib.rc_export_frames_span(
                self._handle, prev_fcount, f_new, _p(frames, _u8p)
            )
            # Sparse: recorded restamps ∪ sweep-invalidated (alive diff —
            # the sweep runs inside rc_add, C-side).
            alive_now = np.empty(self.capacity, np.uint8)
            self._lib.rc_export_alive(self._handle, _p(alive_now, _u8p))
            parts = [np.nonzero(alive_mark != alive_now)[0]]
            if self._dirty:
                parts.append(np.concatenate(self._dirty))
            dirty = np.unique(np.concatenate(parts))
            dirty = np.ascontiguousarray(
                dirty[(dirty >= 0) & (dirty < self.capacity)]
            )
            dmass = np.empty(dirty.shape[0], np.float64)
            self._lib.rc_export_mass(
                self._handle, dirty.shape[0], _p(dirty, _i64p),
                _p(dmass, _f64p),
            )
            src_ids, src_state = self._resolver.state_arrays()
            out = {
                "delta": np.asarray(True),
                "dedup": np.asarray(True),
                "chain_prev": np.asarray([prev_count, prev_fcount], np.int64),
                "chain_mark": np.asarray([count, fcount], np.int64),
                "span_idx": span,
                "span_obs_seq": obs_seq,
                "span_next_seq": next_seq,
                "span_action": action,
                "span_reward": reward,
                "span_discount": discount,
                "span_alive": alive.astype(bool),
                "span_tree": mass,
                "fspan_idx": fspan,
                "fspan_frames": frames,
                "prio_idx": dirty,
                "prio_mass": dmass,
                "prio_alive": alive_now[dirty].astype(bool),
                "cursor": cursor,
                "count": count,
                "fcount": fcount,
                "frame_dead": int(self._lib.rc_frame_dead(self._handle)),
                "dropped_carry": self._resolver.dropped_carry,
                "frame_capacity": self.frame_capacity,
                "src_ids": src_ids,
                "src_state": src_state,
            }
            self._mark_locked(count, cursor, fcount, alive_now)
            return out

    def _mark_locked(self, count, cursor, fcount, alive_now=None) -> None:
        if alive_now is None:
            alive_now = np.empty(self.capacity, np.uint8)
            self._lib.rc_export_alive(self._handle, _p(alive_now, _u8p))
        self._ckpt = (count, cursor, fcount, alive_now)
        self._dirty, self._dirty_rows = [], 0

    def apply_delta_state_dict(self, delta: dict) -> None:
        with self._lock:
            if "delta" not in delta:
                raise ValueError("not a delta snapshot (missing 'delta' key)")
            if int(delta["frame_capacity"]) != self.frame_capacity:
                raise ValueError(
                    f"delta frame ring {int(delta['frame_capacity'])} != "
                    f"configured {self.frame_capacity}"
                )
            prev = np.asarray(delta["chain_prev"]).reshape(-1)
            count, fcount = self.total_added, int(
                self._lib.rc_fcount(self._handle)
            )
            if int(prev[0]) != count or int(prev[1]) != fcount:
                raise ValueError(
                    f"delta chain discontinuity: delta continues "
                    f"(count, fcount)=({int(prev[0])}, {int(prev[1])}), "
                    f"replay is at ({count}, {fcount})"
                )
            n_new = int(delta["count"]) - int(prev[0])
            f_new = int(delta["fcount"]) - int(prev[1])
            start = (int(delta["cursor"]) - n_new) % self.capacity
            self._lib.rc_import_rows(
                self._handle, start, n_new,
                _p(np.ascontiguousarray(delta["span_obs_seq"], np.int64), _i64p),
                _p(np.ascontiguousarray(delta["span_next_seq"], np.int64), _i64p),
                _p(np.ascontiguousarray(delta["span_action"], np.int32), _i32p),
                _p(np.ascontiguousarray(delta["span_reward"], np.float32), _f32p),
                _p(np.ascontiguousarray(delta["span_discount"], np.float32), _f32p),
                _p(np.ascontiguousarray(delta["span_alive"], np.uint8), _u8p),
                _p(np.ascontiguousarray(delta["span_tree"], np.float64), _f64p),
            )
            if self._tier is not None and f_new:
                self._tier.note_write(
                    int(prev[1]) % self.frame_capacity, f_new
                )
            self._lib.rc_import_frames_span(
                self._handle, int(prev[1]), f_new,
                _p(np.ascontiguousarray(delta["fspan_frames"], np.uint8), _u8p),
            )
            pidx = np.ascontiguousarray(delta["prio_idx"], np.int64)
            self._lib.rc_apply_sparse(
                self._handle, pidx.shape[0], _p(pidx, _i64p),
                _p(np.ascontiguousarray(delta["prio_alive"], np.uint8), _u8p),
                _p(np.ascontiguousarray(delta["prio_mass"], np.float64), _f64p),
            )
            self._lib.rc_set_counters(
                self._handle, int(delta["cursor"]), int(delta["count"]),
                int(delta["fcount"]), int(delta["frame_dead"]),
            )
            self._resolver.dropped_carry = int(delta["dropped_carry"])
            self._resolver.load_state_arrays(
                delta["src_ids"], delta["src_state"]
            )
            self._mark_locked(
                int(delta["count"]), int(delta["cursor"]),
                int(delta["fcount"]),
            )

    def load_state_dict(self, state: dict) -> None:
        if "dedup" not in state:
            raise ValueError("snapshot is not a dedup-replay snapshot")
        if int(state["frame_capacity"]) != self.frame_capacity:
            raise ValueError(
                f"snapshot frame ring {int(state['frame_capacity'])} != "
                f"configured {self.frame_capacity}"
            )
        size = state["obs_seq"].shape[0]
        if size > self.capacity:
            raise ValueError("snapshot larger than capacity")
        with self._lock:
            nf = min(int(state["fcount"]), self.frame_capacity)
            tiered_base = "tier_hot_sids" in state
            adopt = False
            if tiered_base:
                from ape_x_dqn_tpu.replay.tiered import read_cold_refs_dense

                span_frames = int(
                    np.asarray(state["tier_span_frames"]).reshape(-1)[0]
                )
                tier_cap = int(
                    np.asarray(state["tier_capacity"]).reshape(-1)[0]
                )
                adopt = (self._tier is not None
                         and self._tier.span_frames == span_frames
                         and self._tier.capacity == tier_cap)
                if adopt:
                    # O(hot) restore: rows import with an empty frame leg;
                    # spans land below (hot inline, cold verified+adopted
                    # in place — the spill file IS the restored data).
                    frames = np.zeros((0, *self.obs_shape), np.uint8)
                else:
                    # Incompatible/no tier: materialize every referenced
                    # span (CRC- and content-verified) into a dense leg.
                    frames = np.ascontiguousarray(
                        read_cold_refs_dense(state)[:nf], np.uint8
                    )
            else:
                frames = np.ascontiguousarray(state["frames"], np.uint8)
            rc = self._lib.rc_import(
                self._handle, frames.shape[0], _p(frames, _u8p), size,
                _p(np.ascontiguousarray(state["obs_seq"], np.int64), _i64p),
                _p(np.ascontiguousarray(state["next_seq"], np.int64), _i64p),
                _p(np.ascontiguousarray(state["action"], np.int32), _i32p),
                _p(np.ascontiguousarray(state["reward"], np.float32), _f32p),
                _p(np.ascontiguousarray(state["discount"], np.float32), _f32p),
                _p(np.ascontiguousarray(
                    state["alive"], np.uint8), _u8p),
                _p(np.ascontiguousarray(
                    state["tree_priorities"], np.float64), _f64p),
                int(state["cursor"]), int(state["count"]),
                int(state["fcount"]),
            )
            if rc != 0:
                raise ValueError("rc_import rejected the snapshot")
            # Accounting parity with the numpy twin: dropped_carry /
            # frame_dead survive resume (pre-incremental snapshots lack
            # the keys — degrade to 0).
            self._lib.rc_set_counters(
                self._handle, int(state["cursor"]), int(state["count"]),
                int(state["fcount"]), int(state.get("frame_dead", 0)),
            )
            self._resolver.dropped_carry = int(state.get("dropped_carry", 0))
            self._resolver.load_state_arrays(
                state["src_ids"], state["src_state"]
            )
            if self._tier is not None:
                self._tier.drop_all()
                if adopt:
                    from ape_x_dqn_tpu.replay.tiered import ColdSpanStore

                    tier = self._tier
                    path = bytes(np.asarray(
                        state["tier_spill_path"], np.uint8)).decode()
                    same = (os.path.realpath(path)
                            == os.path.realpath(tier.store.path))
                    src = tier.store if same else ColdSpanStore(
                        path, tier.n_spans, tier.span_bytes
                    )
                    try:
                        hot_sids = np.asarray(
                            state["tier_hot_sids"], np.int64)
                        hot_frames = np.asarray(state["tier_hot_frames"])
                        off = 0
                        for sid in hot_sids:
                            n = tier._span_len(int(sid))
                            tier.install_hot(
                                int(sid), hot_frames[off:off + n]
                            )
                            off += n
                        for sid, offset, length, crc in zip(
                            np.asarray(state["tier_cold_sids"], np.int64),
                            np.asarray(state["tier_cold_offsets"],
                                       np.int64),
                            np.asarray(state["tier_cold_lens"], np.int64),
                            np.asarray(state["tier_cold_crcs"], np.int64),
                        ):
                            tier.adopt_cold_ref(
                                int(sid), int(offset), int(length),
                                int(crc), src,
                            )
                    finally:
                        if not same:
                            src.close()
                elif nf:
                    # Dense restore into a tiered ring: the whole written
                    # region just landed hot; the evictor trims it back
                    # under budget.
                    self._tier.note_write(0, nf)
            self._ckpt, self._dirty, self._dirty_rows = None, [], 0
