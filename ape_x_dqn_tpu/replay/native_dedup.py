"""ctypes bindings for the native frame-dedup replay core — the
paper-scale host path (round-4 verdict item 1b).

``NativeDedupReplay`` is a drop-in for ``replay.dedup.DedupReplay`` (same
constructor surface + add/sample/update_priorities/size/state_dict), with
every learner-facing operation fused into ONE GIL-released C call
(_native/replay_core.cc): tree descent + IS weights + both frame gathers
in ``rc_sample``; ring writes + priority set + liveness sweep in
``rc_add``.  The sum-tree is striped ``n_stripes`` ways with per-stripe
locks; the striped sampling law matches the sharded device replay's
(equal rows per stripe, IS-corrected) so runs can move between host
stripes and device shards without changing the estimator.  This wrapper
serializes calls under one Python-side lock (carry state lives here), so
striping is law + lock-granularity groundwork — NOT demonstrated
multicore parallelism (this image has one core).  ``n_stripes=1`` is
bit-exact with the numpy twin (tests/test_native_dedup.py pins it).

Build discipline mirrors replay/native.py: compile on first use with g++,
atomic rename, cached .so keyed by source mtime; ``native_dedup_available``
gates callers to the numpy fallback when the toolchain is missing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from ape_x_dqn_tpu.replay.dedup import CarryResolver
from ape_x_dqn_tpu.types import DedupChunk, NStepTransition, PrioritizedBatch

_HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC = os.path.join(_HERE, "_native", "replay_core.cc")
_SO = os.path.join(_HERE, "_native", "replay_core.so")

_lib = None
_lib_err: str | None = None
_lock = threading.Lock()

_i64p = ctypes.POINTER(ctypes.c_int64)
_i32p = ctypes.POINTER(ctypes.c_int32)
_f32p = ctypes.POINTER(ctypes.c_float)
_f64p = ctypes.POINTER(ctypes.c_double)
_u8p = ctypes.POINTER(ctypes.c_uint8)


def _build() -> None:
    tmp = f"{_SO}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.rename(tmp, _SO)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _load():
    global _lib, _lib_err
    with _lock:
        if _lib is not None or _lib_err is not None:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                _build()
            lib = ctypes.CDLL(_SO)
            lib.rc_create.restype = ctypes.c_void_p
            lib.rc_create.argtypes = [
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_double, ctypes.c_int32,
            ]
            lib.rc_destroy.argtypes = [ctypes.c_void_p]
            for name in ("rc_size", "rc_count", "rc_fcount", "rc_cursor",
                         "rc_frame_dead"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_int64
                fn.argtypes = [ctypes.c_void_p]
            for name in ("rc_total", "rc_max"):
                fn = getattr(lib, name)
                fn.restype = ctypes.c_double
                fn.argtypes = [ctypes.c_void_p]
            lib.rc_get_mass.restype = ctypes.c_double
            lib.rc_get_mass.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.rc_add.restype = ctypes.c_int64
            lib.rc_add.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, _u8p, ctypes.c_int64,
                _i64p, _i64p, _i32p, _f32p, _f32p, _f32p,
            ]
            lib.rc_sample.restype = ctypes.c_int32
            lib.rc_sample.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_double, _f64p,
                _i64p, _f64p, _u8p, _u8p, _i32p, _f32p, _f32p,
            ]
            lib.rc_update.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, _i64p, _f32p,
            ]
            lib.rc_export.argtypes = [
                ctypes.c_void_p, _u8p, _i64p, _i64p, _i32p, _f32p, _f32p,
                _u8p, _f64p,
            ]
            lib.rc_import.restype = ctypes.c_int32
            lib.rc_import.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, _u8p, ctypes.c_int64,
                _i64p, _i64p, _i32p, _f32p, _f32p, _u8p, _f64p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ]
            _lib = lib
        except Exception as e:  # compiler missing, build/load failure
            _lib_err = f"{type(e).__name__}: {e}"
        return _lib


def native_dedup_available() -> bool:
    return _load() is not None


def native_dedup_error() -> str | None:
    _load()
    return _lib_err


def _p(a: np.ndarray, ptr_t):
    return a.ctypes.data_as(ptr_t)


class NativeDedupReplay:
    """C++-core frame-dedup prioritized replay (interface of DedupReplay)."""

    def __init__(
        self,
        capacity: int,
        obs_shape,
        priority_exponent: float = 0.6,
        obs_dtype=np.uint8,
        frame_ratio: float = 1.25,
        n_stripes: int = 1,
    ):
        lib = _load()
        if lib is None:
            raise RuntimeError(f"native replay core unavailable: {_lib_err}")
        if np.dtype(obs_dtype) != np.uint8:
            raise ValueError("native dedup core stores uint8 frames")
        self._lib = lib
        self.capacity = int(capacity)
        self.frame_capacity = max(1, int(round(capacity * frame_ratio)))
        self.obs_shape = tuple(obs_shape)
        self.frame_bytes = int(np.prod(self.obs_shape))
        self.alpha = float(priority_exponent)
        self.n_stripes = int(n_stripes)
        self._handle = lib.rc_create(
            self.capacity, self.frame_capacity, self.frame_bytes,
            self.alpha, self.n_stripes,
        )
        if not self._handle:
            raise MemoryError("rc_create failed")
        self._resolver = CarryResolver()
        self._lock = threading.Lock()

    def __del__(self):
        h = getattr(self, "_handle", None)
        if h:
            self._lib.rc_destroy(h)
            self._handle = None

    # -- write path ------------------------------------------------------

    def add(self, priorities: np.ndarray, chunk: DedupChunk) -> np.ndarray:
        prio = np.ascontiguousarray(priorities, np.float32)
        frames = np.ascontiguousarray(chunk.frames, np.uint8)
        U, M = frames.shape[0], prio.shape[0]
        if M > self.capacity or U > self.frame_capacity:
            raise ValueError("chunk exceeds ring capacity")
        with self._lock:
            base = int(self._lib.rc_fcount(self._handle))
            obs_seq, next_seq, keep = self._resolver.resolve(chunk, base)
            obs_seq = np.ascontiguousarray(obs_seq[keep])
            next_seq = np.ascontiguousarray(next_seq[keep])
            action = np.ascontiguousarray(chunk.action, np.int32)[keep]
            reward = np.ascontiguousarray(chunk.reward, np.float32)[keep]
            discount = np.ascontiguousarray(chunk.discount, np.float32)[keep]
            pk = np.ascontiguousarray(prio[keep])
            m = obs_seq.shape[0]
            first = self._lib.rc_add(
                self._handle, U, _p(frames, _u8p), m,
                _p(obs_seq, _i64p), _p(next_seq, _i64p),
                _p(action, _i32p), _p(reward, _f32p),
                _p(discount, _f32p), _p(pk, _f32p),
            )
            if first < 0:
                raise ValueError("rc_add rejected the chunk (size violation)")
            return (first + np.arange(m, dtype=np.int64)) % self.capacity

    # -- read path -------------------------------------------------------

    def sample(
        self,
        batch_size: int,
        beta: float = 0.4,
        rng: Optional[np.random.Generator] = None,
    ) -> PrioritizedBatch:
        rng = rng or np.random.default_rng()
        B = int(batch_size)
        u = np.ascontiguousarray(rng.random(B))
        idx = np.empty(B, np.int64)
        weights = np.empty(B, np.float64)
        obs = np.empty((B, *self.obs_shape), np.uint8)
        next_obs = np.empty((B, *self.obs_shape), np.uint8)
        action = np.empty(B, np.int32)
        reward = np.empty(B, np.float32)
        discount = np.empty(B, np.float32)
        with self._lock:
            rc = self._lib.rc_sample(
                self._handle, B, float(beta), _p(u, _f64p),
                _p(idx, _i64p), _p(weights, _f64p), _p(obs, _u8p),
                _p(next_obs, _u8p), _p(action, _i32p),
                _p(reward, _f32p), _p(discount, _f32p),
            )
        if rc == -1:
            raise ValueError("cannot sample from an empty replay")
        if rc == -2:
            raise ValueError(
                f"batch_size {B} must divide by n_stripes {self.n_stripes}"
            )
        return PrioritizedBatch(
            transition=NStepTransition(
                obs=obs, action=action, reward=reward,
                discount=discount, next_obs=next_obs,
            ),
            indices=idx.astype(np.int32),
            is_weights=weights.astype(np.float32),
        )

    def update_priorities(self, indices, priorities) -> None:
        idx = np.ascontiguousarray(indices, np.int64)
        prio = np.ascontiguousarray(priorities, np.float32)
        if idx.size == 0:
            return
        with self._lock:
            self._lib.rc_update(
                self._handle, idx.shape[0], _p(idx, _i64p), _p(prio, _f32p)
            )

    # -- misc ------------------------------------------------------------

    def size(self) -> int:
        return int(self._lib.rc_size(self._handle))

    @property
    def total_added(self) -> int:
        return int(self._lib.rc_count(self._handle))

    @property
    def stats(self) -> dict:
        return {
            "frame_dead": int(self._lib.rc_frame_dead(self._handle)),
            "dropped_carry": self._resolver.dropped_carry,
        }

    def frames_nbytes(self) -> int:
        return self.frame_capacity * self.frame_bytes

    def max_priority(self) -> float:
        m = float(self._lib.rc_max(self._handle))
        return float(m ** (1.0 / self.alpha)) if m > 0 else 1.0

    # -- snapshot --------------------------------------------------------

    def state_dict(self) -> dict:
        with self._lock:
            size = self.size()
            nf = min(int(self._lib.rc_fcount(self._handle)),
                     self.frame_capacity)
            frames = np.empty((nf, *self.obs_shape), np.uint8)
            obs_seq = np.empty(size, np.int64)
            next_seq = np.empty(size, np.int64)
            action = np.empty(size, np.int32)
            reward = np.empty(size, np.float32)
            discount = np.empty(size, np.float32)
            alive = np.empty(size, np.uint8)
            mass = np.empty(size, np.float64)
            self._lib.rc_export(
                self._handle, _p(frames, _u8p), _p(obs_seq, _i64p),
                _p(next_seq, _i64p), _p(action, _i32p), _p(reward, _f32p),
                _p(discount, _f32p), _p(alive, _u8p), _p(mass, _f64p),
            )
            src_ids, src_state = self._resolver.state_arrays()
            return {
                "dedup": np.asarray(True),
                "frames": frames, "obs_seq": obs_seq, "next_seq": next_seq,
                "action": action, "reward": reward, "discount": discount,
                "alive": alive.astype(bool),
                "tree_priorities": mass,
                "cursor": int(self._lib.rc_cursor(self._handle)),
                "count": self.total_added,
                "fcount": int(self._lib.rc_fcount(self._handle)),
                "frame_capacity": self.frame_capacity,
                "src_ids": src_ids, "src_state": src_state,
            }

    def load_state_dict(self, state: dict) -> None:
        if "dedup" not in state:
            raise ValueError("snapshot is not a dedup-replay snapshot")
        if int(state["frame_capacity"]) != self.frame_capacity:
            raise ValueError(
                f"snapshot frame ring {int(state['frame_capacity'])} != "
                f"configured {self.frame_capacity}"
            )
        size = state["obs_seq"].shape[0]
        if size > self.capacity:
            raise ValueError("snapshot larger than capacity")
        with self._lock:
            frames = np.ascontiguousarray(state["frames"], np.uint8)
            rc = self._lib.rc_import(
                self._handle, frames.shape[0], _p(frames, _u8p), size,
                _p(np.ascontiguousarray(state["obs_seq"], np.int64), _i64p),
                _p(np.ascontiguousarray(state["next_seq"], np.int64), _i64p),
                _p(np.ascontiguousarray(state["action"], np.int32), _i32p),
                _p(np.ascontiguousarray(state["reward"], np.float32), _f32p),
                _p(np.ascontiguousarray(state["discount"], np.float32), _f32p),
                _p(np.ascontiguousarray(
                    state["alive"], np.uint8), _u8p),
                _p(np.ascontiguousarray(
                    state["tree_priorities"], np.float64), _f64p),
                int(state["cursor"]), int(state["count"]),
                int(state["fcount"]),
            )
            if rc != 0:
                raise ValueError("rc_import rejected the snapshot")
            self._resolver.load_state_arrays(
                state["src_ids"], state["src_state"]
            )
