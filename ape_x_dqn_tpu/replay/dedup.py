"""Frame-dedup prioritized replay — each frame stored ONCE (host path).

Round-4 verdict item 1a: ``PrioritizedReplay`` (replay/buffer.py) carries
full ``obs`` AND ``next_obs`` arrays — the direct cause of config3's 28 GB
at 2M slots (it would be ~14 GB stored once) and a 2× tax on host RAM,
snapshot size, and ingest bandwidth.  This buffer stores a single FRAME
RING plus per-transition frame references (types.DedupChunk wire format,
produced by ``ActorFleet(emit_dedup=True)``):

  * **frame ring** — ``frame_capacity ≈ frame_ratio × capacity`` unique
    observations addressed by a monotone int64 sequence number (slot =
    seq % Cf).  Steady-state arrival is ~1 frame per transition (the
    sliding-window emission shares every interior frame between the
    transition that uses it as S_t and the one n earlier that uses it as
    S_{t+n}, and consecutive chunks carry their n-row overlap), so the
    default ``frame_ratio=1.25`` leaves slack for truncation extras and
    source interleaving while still cutting storage ~1.6-2×.
  * **transition ring** — (obs_seq, next_seq, action, reward, discount)
    per slot, FIFO like the double-store; the sum-tree is unchanged.
  * **invalidation sweep** — when new frames overwrite ring slots, any
    transition whose ``obs_seq`` fell out of the live window gets its
    priority zeroed (one vectorized compare per add), so a sampled
    transition's frames are ALWAYS its own: the ring can never pair a
    stale transition with a recycled frame.  ``update_priorities`` applies
    the same liveness guard, so a deferred learner restamp cannot
    resurrect a frame-dead slot.

Same sampling law, IS weights, and FIFO semantics as ``PrioritizedReplay``
(equal-semantics tests: tests/test_dedup.py); reference capability mapping
identical to replay/buffer.py (reference replay.py:8-83).

A C++ twin of this structure lives in ``_native/replay_core.cc``
(replay/native_dedup.py) for the paper-scale host path; this numpy version
is the always-available fallback and the oracle the native one is pinned
against.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ape_x_dqn_tpu.types import DedupChunk, NStepTransition, PrioritizedBatch


class CarryResolver:
    """Per-source ref resolution shared by every dedup consumer (numpy
    DedupReplay below, the native core's wrapper, tests): maps a chunk's
    relative refs to absolute frame seqs given the consumer's frame
    counter, tracking (chunk_seq, base, U) per source; a continuity gap
    drops only the carried rows."""

    def __init__(self, max_sources: int = 4096):
        self.sources: dict = {}   # src -> (chunk_seq, frame_base, U)
        self.dropped_carry = 0
        self._max_sources = max_sources

    def resolve(self, chunk: DedupChunk, base: int):
        """-> (obs_seq int64 [M], next_seq int64 [M], keep bool [M]);
        ``base`` is the consumer's frame count where this chunk's frames
        will land.  Updates the source record."""
        prev = self.sources.get(chunk.source)
        contiguous = (
            prev is not None
            and chunk.chunk_seq == prev[0] + 1
            and chunk.prev_frames == prev[2]
        )
        obs_seq = base + np.asarray(chunk.obs_ref, np.int64)
        next_seq = base + np.asarray(chunk.next_ref, np.int64)
        neg = chunk.obs_ref < 0
        if neg.any():
            if contiguous:
                obs_seq[neg] = prev[1] + prev[2] + chunk.obs_ref[neg]
                keep = np.ones(len(obs_seq), bool)
            else:
                keep = ~neg
                self.dropped_carry += int(neg.sum())
        else:
            keep = np.ones(len(obs_seq), bool)
        self.sources[chunk.source] = (
            chunk.chunk_seq, base, chunk.frames.shape[0]
        )
        if len(self.sources) > self._max_sources:
            for key in sorted(
                self.sources, key=lambda s: self.sources[s][1]
            )[: len(self.sources) // 2]:
                del self.sources[key]
        return obs_seq, next_seq, keep

    def state_arrays(self):
        src = self.sources
        return (
            np.array(list(src.keys()), np.int64),
            np.array([list(v) for v in src.values()], np.int64)
            .reshape(len(src), 3),
        )

    def load_state_arrays(self, ids, rows):
        self.sources = {
            int(s): tuple(int(x) for x in row)
            for s, row in zip(ids, rows)
        }


class DedupReplay:
    """Prioritized n-step transition store over a shared frame ring.

    Args mirror ``PrioritizedReplay`` plus:
      frame_ratio: frame-ring slots per transition slot.  Must cover the
        actual frame/transition arrival ratio (≈ (flush_every + n_step) /
        flush_every for overlapping emission, + truncation extras) or the
        frame ring wraps early and the oldest transitions are invalidated
        before their FIFO death — gracefully (they become unsampleable),
        but effective capacity shrinks.  ``stats["frame_dead"]`` counts
        those; size the ratio so it stays ~0.
    """

    def __init__(
        self,
        capacity: int,
        obs_shape,
        priority_exponent: float = 0.6,
        obs_dtype=np.uint8,
        sum_tree_cls=None,
        frame_ratio: float = 1.25,
        hot_frame_budget_bytes: int = 0,
        spill_dir: Optional[str] = None,
        spill_span_frames: int = 0,
        spill_watermark_high: float = 1.0,
        spill_watermark_low: float = 0.9,
    ):
        if sum_tree_cls is None:
            from ape_x_dqn_tpu.replay.native import default_sum_tree_cls

            sum_tree_cls = default_sum_tree_cls()
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        if frame_ratio <= 0:
            raise ValueError("frame_ratio must be positive")
        self.capacity = int(capacity)
        self.frame_capacity = max(1, int(round(capacity * frame_ratio)))
        self.alpha = float(priority_exponent)
        # Tiered frame store (replay/tiered.py): a positive hot budget
        # replaces the dense frame ring with a hot span cache over a
        # CRC-framed cold spill file.  Only the frame BYTES tier — the
        # sum-tree, liveness, and every transition column stay hot, so
        # the sampling law and update_priorities are untouched.  Off
        # (the default) this branch allocates the dense ndarray exactly
        # as before: zero cost when disabled.
        self._tier = None
        if hot_frame_budget_bytes > 0:
            import os

            from ape_x_dqn_tpu.replay.tiered import TieredFrameRing

            if spill_dir is None:
                raise ValueError("tiered replay needs a spill_dir")
            self._tier = TieredFrameRing(
                self.frame_capacity, obs_shape, dtype=obs_dtype,
                hot_budget_bytes=hot_frame_budget_bytes,
                spill_path=os.path.join(spill_dir, "frames.cold"),
                span_frames=spill_span_frames,
                watermark_high=spill_watermark_high,
                watermark_low=spill_watermark_low,
            )
            self._frames = None
        else:
            self._frames = np.zeros(
                (self.frame_capacity, *obs_shape), obs_dtype
            )
        self._obs_seq = np.zeros((capacity,), np.int64)
        self._next_seq = np.zeros((capacity,), np.int64)
        self._action = np.zeros((capacity,), np.int32)
        self._reward = np.zeros((capacity,), np.float32)
        self._discount = np.zeros((capacity,), np.float32)
        self._alive = np.zeros((capacity,), bool)
        self._tree = sum_tree_cls(capacity)
        self._cursor = 0
        self._count = 0          # transitions ever accepted
        self._fcount = 0         # frames ever written (monotone seq)
        self._resolver = CarryResolver()
        self._frame_dead = 0
        self._lock = threading.Lock()
        # Incremental-checkpoint dirty tracking (utils/checkpoint_inc):
        # (count, cursor, fcount) at the last delta snapshot + the sparse
        # indices restamped/swept since.  None = next snapshot is a base.
        self._ckpt = None
        self._dirty: list = []
        self._dirty_rows = 0

    # -- write path (actors / drain) ------------------------------------

    def add(self, priorities: np.ndarray, chunk: DedupChunk) -> np.ndarray:
        """Ingest one dedup chunk; returns the transition slots written.

        Carry refs resolve against this source's previous chunk; a
        ``chunk_seq`` gap or frame-count mismatch (dropped chunk, worker
        respawn without a bootstrap) drops just the carried rows, counted
        in ``stats["dropped_carry"]``.
        """
        priorities = np.asarray(priorities, dtype=np.float64)
        U = chunk.frames.shape[0]
        M = priorities.shape[0]
        if M != chunk.action.shape[0]:
            raise ValueError("priorities/chunk length mismatch")
        if M > self.capacity:
            raise ValueError(f"chunk of {M} exceeds capacity {self.capacity}")
        if U > self.frame_capacity:
            raise ValueError(
                f"chunk of {U} frames exceeds frame ring {self.frame_capacity}"
            )
        with self._lock:
            base = self._fcount
            obs_seq, next_seq, keep = self._resolver.resolve(chunk, base)
            # Frames land regardless of dropped rows (the NEXT chunk's
            # carry refs point into them).
            if self._tier is not None:
                self._tier.put_span(base % self.frame_capacity, U,
                                    chunk.frames)
            else:
                fidx = (base + np.arange(U)) % self.frame_capacity
                self._frames[fidx] = chunk.frames
            self._fcount = base + U
            m = int(keep.sum())
            idx = np.zeros(0, np.int64)
            if m:
                idx = (self._cursor + np.arange(m)) % self.capacity
                self._obs_seq[idx] = obs_seq[keep]
                self._next_seq[idx] = next_seq[keep]
                self._action[idx] = chunk.action[keep]
                self._reward[idx] = chunk.reward[keep]
                self._discount[idx] = chunk.discount[keep]
                self._alive[idx] = True
                self._tree.set(
                    idx,
                    np.power(np.maximum(priorities[keep], 1e-12), self.alpha),
                )
                self._cursor = int((self._cursor + m) % self.capacity)
                self._count += m
            self._sweep_locked()
            return idx

    def _sweep_locked(self) -> None:
        """Zero the priority of transitions whose obs frame was overwritten
        (obs_seq is each row's OLDEST ref — the DedupChunk layout contract)."""
        fmin = self._fcount - self.frame_capacity
        if fmin <= 0:
            return
        dead = self._alive & (self._obs_seq < fmin)
        if dead.any():
            di = np.nonzero(dead)[0]
            self._tree.set(di, np.zeros(len(di)))
            self._alive[di] = False
            self._frame_dead += len(di)
            self._track_dirty_locked(di)

    def _track_dirty_locked(self, indices: np.ndarray) -> None:
        if self._ckpt is None:
            return
        self._dirty.append(np.array(indices, np.int64, copy=True))
        self._dirty_rows += len(indices)
        if self._dirty_rows > 4 * self.capacity:
            # Overflow guard: sparse record rivals a base — retrack.
            self._dirty, self._dirty_rows, self._ckpt = [], 0, None

    def _fgather(self, seqs: np.ndarray) -> np.ndarray:
        """Frame gather by sequence number — the ONE indirection the tier
        adds to the sample path (cold spans fault here)."""
        slots = np.asarray(seqs, np.int64) % self.frame_capacity
        if self._tier is not None:
            return self._tier.get(slots)
        return self._frames[slots]

    # -- cold tier surface (replay/tiered.py; no-ops when tier is off) ---

    @property
    def tier(self):
        return self._tier

    def tier_over_watermark(self) -> bool:
        """Lock-free evictor poll: a stale read only delays one batch."""
        return self._tier is not None and self._tier.over_high_watermark()

    def spill_cold(self, max_spans: int = 0, target_bytes=None) -> tuple:
        """Evict least-recently-sampled spans down to the low watermark
        (TierEvictor's entry point — one bounded batch per lock hold).
        ``target_bytes`` overrides the watermark (0 = spill everything —
        bench/drain tooling)."""
        if self._tier is None:
            return 0, 0
        with self._lock:
            return self._tier.spill(max_spans=max_spans,
                                    target_bytes=target_bytes)

    def tier_flush_dirty(self) -> int:
        """Write-back every dirty hot span's cold record (residency kept)
        under the replay lock — pre-trim/pre-bench hygiene."""
        if self._tier is None:
            return 0
        with self._lock:
            return self._tier.flush_dirty()

    def tier_stats(self) -> Optional[dict]:
        if self._tier is None:
            return None
        with self._lock:
            return self._tier.tier_stats()

    # -- read path (learner) --------------------------------------------

    def sample(
        self,
        batch_size: int,
        beta: float = 0.4,
        rng: Optional[np.random.Generator] = None,
    ) -> PrioritizedBatch:
        """Stratified proportional sample with IS weights — the law and
        weight math of ``PrioritizedReplay.sample`` verbatim; only the
        frame gather goes through the ref indirection."""
        rng = rng or np.random.default_rng()
        with self._lock:
            size = min(self._count, self.capacity)
            if size == 0:
                raise ValueError("cannot sample from an empty replay")
            idx = self._tree.sample_stratified(batch_size, rng)
            mass = self._tree.get(idx)
            total = self._tree.total
            transition = NStepTransition(
                obs=self._fgather(self._obs_seq[idx]),
                action=self._action[idx].copy(),
                reward=self._reward[idx].copy(),
                discount=self._discount[idx].copy(),
                next_obs=self._fgather(self._next_seq[idx]),
            )
        probs = mass / total
        weights = np.power(size * np.maximum(probs, 1e-12), -beta)
        weights = (weights / weights.max()).astype(np.float32)
        return PrioritizedBatch(
            transition=transition,
            indices=idx.astype(np.int32),
            is_weights=weights,
        )

    def update_priorities(self, indices: np.ndarray, priorities: np.ndarray) -> None:
        """Learner priority feedback, with the liveness guard: a restamp
        must not resurrect a frame-dead slot (its frames belong to newer
        transitions now — sampling it would pair stale metadata with
        recycled pixels).  Slot-recycled-by-a-newer-transition keeps the
        double-store's benign self-correcting race."""
        indices = np.asarray(indices, dtype=np.int64)
        priorities = np.asarray(priorities, dtype=np.float64)
        if indices.size == 0:
            return
        with self._lock:
            fmin = self._fcount - self.frame_capacity
            live = self._alive[indices] & (self._obs_seq[indices] >= fmin)
            if live.any():
                self._tree.set(
                    indices[live],
                    np.power(
                        np.maximum(priorities[live], 1e-12), self.alpha
                    ),
                )
                self._track_dirty_locked(indices[live])

    # -- misc ------------------------------------------------------------

    @property
    def stats(self) -> dict:
        return {
            "frame_dead": self._frame_dead,
            "dropped_carry": self._resolver.dropped_carry,
        }

    def size(self) -> int:
        with self._lock:
            return min(self._count, self.capacity)

    @property
    def total_added(self) -> int:
        return self._count

    def frames_nbytes(self) -> int:
        """Bytes held by frame storage in DRAM — the dedup win's observable
        (compare: the double-store's 2 × capacity × frame_bytes).  Tiered,
        this is the HOT bytes only — the number the hot budget caps."""
        if self._tier is not None:
            with self._lock:
                return self._tier.hot_bytes
        return self._frames.nbytes

    def max_priority(self) -> float:
        with self._lock:
            m = self._tree.max_priority()
        return float(m ** (1.0 / self.alpha)) if m > 0 else 1.0

    # -- snapshot (checkpointing) ----------------------------------------

    def state_dict(self) -> dict:
        with self._lock:
            return self._state_dict_locked()

    def _state_dict_locked(self, cold_refs: bool = False) -> dict:
        size = min(self._count, self.capacity)
        idx = np.arange(size)
        nf = min(self._fcount, self.frame_capacity)
        src_ids, src_state = self._resolver.state_arrays()
        out = {
            "dedup": np.asarray(True),
            "frames": None,  # filled below (dense or tier cold refs)
            "obs_seq": self._obs_seq[:size].copy(),
            "next_seq": self._next_seq[:size].copy(),
            "action": self._action[:size].copy(),
            "reward": self._reward[:size].copy(),
            "discount": self._discount[:size].copy(),
            "alive": self._alive[:size].copy(),
            "tree_priorities": self._tree.get(idx),
            "cursor": self._cursor,
            "count": self._count,
            "fcount": self._fcount,
            "frame_dead": self._frame_dead,
            "dropped_carry": self._resolver.dropped_carry,
            "frame_capacity": self.frame_capacity,
            "src_ids": src_ids,
            "src_state": src_state,
        }
        # Frame leg.  Dense (or tier-but-nothing-cold): the legacy "frames"
        # array.  cold_refs=True with cold spans: the tiered base format —
        # hot frames inline, cold spans referenced by (offset, len, crc)
        # into the spill file instead of being paged back in (the
        # checkpoint_inc "mostly-cold base must not re-read the cold
        # tier" contract).  state_dict() keeps cold_refs=False: the
        # public full snapshot always materializes (oracle comparisons,
        # legacy npz path).
        refs = None
        if cold_refs and self._tier is not None:
            refs = self._tier.cold_refs(nf)
        if refs is not None:
            del out["frames"]
            out.update(refs)
        elif self._tier is not None:
            out["frames"] = self._tier.get_span(0, nf)
        else:
            out["frames"] = self._frames[:nf].copy()
        return out

    # -- incremental snapshot (utils/checkpoint_inc delta protocol) -------

    def delta_state_dict(self, force_base: bool = False) -> dict:
        """Base or dirty-span delta since the last snapshot.  The frame
        ring and transition ring write sequentially at cursors, so the
        delta is the two spans written since the mark plus the sparse
        restamped/swept priorities — bytes ∝ checkpoint interval, not the
        17.6 GB ring (the whole point; see checkpoint_inc)."""
        with self._lock:
            prev = self._ckpt
            n_new = self._count - (prev[0] if prev else 0)
            f_new = self._fcount - (prev[2] if prev else 0)
            if (force_base or prev is None or n_new >= self.capacity
                    or f_new >= self.frame_capacity):
                # Base snapshots reference cold spans by offset (tiered) —
                # a mostly-cold ring must not be paged back in to save.
                out = self._state_dict_locked(cold_refs=True)
                out["chain_mark"] = np.asarray(
                    [self._count, self._fcount], np.int64
                )
                self._mark_locked()
                return out
            prev_count, prev_cursor, prev_fcount = prev
            span = (prev_cursor + np.arange(n_new)) % self.capacity
            fspan = (prev_fcount + np.arange(f_new)) % self.frame_capacity
            dirty = self._drain_dirty_locked()
            src_ids, src_state = self._resolver.state_arrays()
            out = {
                "delta": np.asarray(True),
                "dedup": np.asarray(True),
                "chain_prev": np.asarray([prev_count, prev_fcount], np.int64),
                "chain_mark": np.asarray(
                    [self._count, self._fcount], np.int64
                ),
                "span_idx": span,
                "span_obs_seq": self._obs_seq[span].copy(),
                "span_next_seq": self._next_seq[span].copy(),
                "span_action": self._action[span].copy(),
                "span_reward": self._reward[span].copy(),
                "span_discount": self._discount[span].copy(),
                "span_alive": self._alive[span].copy(),
                "span_tree": self._tree.get(span),
                "fspan_idx": fspan,
                "fspan_frames": (
                    self._tier.get_span(
                        prev_fcount % self.frame_capacity, f_new
                    )
                    if self._tier is not None
                    else self._frames[fspan].copy()
                ),
                "prio_idx": dirty,
                "prio_mass": self._tree.get(dirty),
                "prio_alive": self._alive[dirty].copy(),
                "cursor": self._cursor,
                "count": self._count,
                "fcount": self._fcount,
                "frame_dead": self._frame_dead,
                "dropped_carry": self._resolver.dropped_carry,
                "frame_capacity": self.frame_capacity,
                "src_ids": src_ids,
                "src_state": src_state,
            }
            self._mark_locked()
            return out

    def _mark_locked(self) -> None:
        self._ckpt = (self._count, self._cursor, self._fcount)
        self._dirty, self._dirty_rows = [], 0

    def _drain_dirty_locked(self) -> np.ndarray:
        if not self._dirty:
            return np.zeros((0,), np.int64)
        idx = np.unique(np.concatenate(self._dirty))
        return idx[(idx >= 0) & (idx < self.capacity)]

    def apply_delta_state_dict(self, delta: dict) -> None:
        """Restore-side replay of one delta; chain discontinuities raise."""
        with self._lock:
            if "delta" not in delta:
                raise ValueError("not a delta snapshot (missing 'delta' key)")
            if int(delta["frame_capacity"]) != self.frame_capacity:
                raise ValueError(
                    f"delta frame ring {int(delta['frame_capacity'])} != "
                    f"configured {self.frame_capacity}"
                )
            prev = np.asarray(delta["chain_prev"]).reshape(-1)
            if int(prev[0]) != self._count or int(prev[1]) != self._fcount:
                raise ValueError(
                    f"delta chain discontinuity: delta continues "
                    f"(count, fcount)=({int(prev[0])}, {int(prev[1])}), "
                    f"replay is at ({self._count}, {self._fcount})"
                )
            span = np.asarray(delta["span_idx"], np.int64)
            fspan = np.asarray(delta["fspan_idx"], np.int64)
            if self._tier is not None:
                if fspan.size:
                    self._tier.put_span(int(fspan[0]), fspan.size,
                                        delta["fspan_frames"])
            else:
                self._frames[fspan] = delta["fspan_frames"]
            self._obs_seq[span] = delta["span_obs_seq"]
            self._next_seq[span] = delta["span_next_seq"]
            self._action[span] = delta["span_action"]
            self._reward[span] = delta["span_reward"]
            self._discount[span] = delta["span_discount"]
            self._alive[span] = np.asarray(delta["span_alive"], bool)
            self._tree.set(span, np.asarray(delta["span_tree"], np.float64))
            prio_idx = np.asarray(delta["prio_idx"], np.int64)
            if prio_idx.size:
                self._tree.set(
                    prio_idx, np.asarray(delta["prio_mass"], np.float64)
                )
                self._alive[prio_idx] = np.asarray(delta["prio_alive"], bool)
            self._cursor = int(delta["cursor"]) % self.capacity
            self._count = int(delta["count"])
            self._fcount = int(delta["fcount"])
            self._frame_dead = int(delta["frame_dead"])
            self._resolver.dropped_carry = int(delta["dropped_carry"])
            self._resolver.load_state_arrays(
                delta["src_ids"], delta["src_state"]
            )
            self._mark_locked()

    def load_state_dict(self, state: dict) -> None:
        if "dedup" not in state:
            raise ValueError(
                "snapshot is not a dedup-replay snapshot (double-store "
                "snapshots don't carry frame refs; re-collect instead)"
            )
        if int(state["frame_capacity"]) != self.frame_capacity:
            raise ValueError(
                f"snapshot frame ring {int(state['frame_capacity'])} != "
                f"configured {self.frame_capacity} — frame slots are "
                "addressed seq % capacity, so the layout must match"
            )
        with self._lock:
            size = state["obs_seq"].shape[0]
            if size > self.capacity:
                raise ValueError("snapshot larger than capacity")
            self._tree.set(
                np.arange(self.capacity), np.zeros(self.capacity)
            )
            self._alive[:] = False
            self._fcount = int(state["fcount"])
            nf = min(self._fcount, self.frame_capacity)
            # Snapshot frames are SLOT-ordered [0, nf): identity placement
            # (seq % capacity addressing is stable across save/restore
            # because frame_capacity is layout-checked above).
            self._load_frames_locked(state, nf)
            rng = np.arange(size)
            self._obs_seq[:size] = state["obs_seq"]
            self._next_seq[:size] = state["next_seq"]
            self._action[:size] = state["action"]
            self._reward[:size] = state["reward"]
            self._discount[:size] = state["discount"]
            self._alive[:size] = state["alive"]
            self._tree.set(rng, state["tree_priorities"])
            self._cursor = int(state["cursor"]) % self.capacity
            self._count = int(state["count"])
            # dropped_carry/frame_dead accounting survives resume (absent
            # in pre-incremental snapshots — degrade to 0, not a crash).
            self._frame_dead = int(state.get("frame_dead", 0))
            self._resolver.dropped_carry = int(state.get("dropped_carry", 0))
            self._resolver.load_state_arrays(
                state["src_ids"], state["src_state"]
            )
            self._ckpt, self._dirty, self._dirty_rows = None, [], 0

    def _load_frames_locked(self, state: dict, nf: int) -> None:
        """Frame leg of a full restore: dense snapshots land as before;
        tiered (cold-ref) bases either ADOPT the spill file in place —
        verify each referenced record, O(hot bytes) restored — or
        materialize through ``read_cold_refs_dense`` when this replay
        has no compatible tier.  Either way every cold byte is CRC- and
        content-verified; a torn record raises the typed
        ``ColdSpanCorrupt`` the checkpoint fallback walk consumes."""
        if "tier_hot_sids" not in state:
            if self._tier is not None:
                self._tier.drop_all()
                self._tier.put_span(0, nf, state["frames"][:nf])
            else:
                self._frames[:nf] = state["frames"][:nf]
            return
        from ape_x_dqn_tpu.replay.tiered import (
            ColdSpanStore,
            read_cold_refs_dense,
        )

        span_frames = int(
            np.asarray(state["tier_span_frames"]).reshape(-1)[0]
        )
        tier_cap = int(np.asarray(state["tier_capacity"]).reshape(-1)[0])
        if (self._tier is None
                or self._tier.span_frames != span_frames
                or self._tier.capacity != tier_cap):
            dense = read_cold_refs_dense(state)
            if self._tier is not None:
                self._tier.drop_all()
                self._tier.put_span(0, nf, dense[:nf])
            else:
                self._frames[:nf] = dense[:nf]
            return
        tier = self._tier
        tier.drop_all()
        path = bytes(
            np.asarray(state["tier_spill_path"], np.uint8)
        ).decode()
        import os

        same = (os.path.realpath(path)
                == os.path.realpath(tier.store.path))
        src = tier.store if same else ColdSpanStore(
            path, tier.n_spans, tier.span_bytes
        )
        try:
            hot_sids = np.asarray(state["tier_hot_sids"], np.int64)
            hot_frames = np.asarray(state["tier_hot_frames"])
            off = 0
            for sid in hot_sids:
                n = tier._span_len(int(sid))
                tier.put_span(int(sid) * span_frames, n,
                              hot_frames[off:off + n])
                off += n
            for sid, offset, length, crc in zip(
                np.asarray(state["tier_cold_sids"], np.int64),
                np.asarray(state["tier_cold_offsets"], np.int64),
                np.asarray(state["tier_cold_lens"], np.int64),
                np.asarray(state["tier_cold_crcs"], np.int64),
            ):
                tier.adopt_cold_ref(int(sid), int(offset), int(length),
                                    int(crc), src)
        finally:
            if not same:
                src.close()
