"""The fused learner step — one XLA program per gradient update.

This is the north-star fusion (BASELINE.json): everything the reference
learner does per update across four call sites and three host↔host RPCs
(reference learner.py:63-80 — sample unpack, double-Q target, TD error, loss,
RMSProp step, target-net sync, priority computation) compiles into a single
jitted function:

    train_step(state, batch) -> (new_state, StepMetrics)

Semantics implemented are the *intended* ones (SURVEY §2.8 defect register):
  * target net copies every ``target_sync_freq`` steps (the reference's modulo
    gate is inverted — learner.py:60);
  * per-transition priorities (the reference collapses them — learner.py:50);
  * terminal masking via the n-step discount (the reference bootstraps through
    episode ends);
  * RMSProp decay is decay, not L2 weight-decay (learner.py:26 misroutes it).

The returned function is pure and donation-friendly: ``state`` is donated so
params/opt-state update in place in HBM.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct

from ape_x_dqn_tpu.ops import losses
from ape_x_dqn_tpu.types import PrioritizedBatch, TrainState


@struct.dataclass
class StepMetrics:
    loss: jax.Array            # float32 []
    mean_abs_td: jax.Array     # float32 []
    max_abs_td: jax.Array      # float32 []
    priorities: jax.Array      # float32 [B] — new replay priorities
    mean_q: jax.Array          # float32 []


def make_optimizer(
    kind: str = "rmsprop",
    learning_rate: float = 0.00025 / 4,
    rmsprop_decay: float = 0.95,
    rmsprop_eps: float = 1.5e-7,
    adam_b1: float = 0.9,
    adam_b2: float = 0.999,
    max_grad_norm: float | None = 40.0,
) -> optax.GradientTransformation:
    """Reference-parity RMSProp (lr 0.00025/4, eps 1.5e-7 — learner.py:26,
    with decay routed correctly) or Adam, with optional grad clipping."""
    if kind == "rmsprop":
        opt = optax.rmsprop(learning_rate, decay=rmsprop_decay, eps=rmsprop_eps)
    elif kind == "adam":
        opt = optax.adam(learning_rate, b1=adam_b1, b2=adam_b2)
    else:
        raise ValueError(f"unknown optimizer kind: {kind}")
    if max_grad_norm is not None:
        opt = optax.chain(optax.clip_by_global_norm(max_grad_norm), opt)
    return opt


def init_train_state(
    network: nn.Module,
    optimizer: optax.GradientTransformation,
    rng: jax.Array,
    sample_obs: jax.Array,
) -> TrainState:
    """Initialize params/target/opt-state from one example observation batch."""
    params = network.init(rng, sample_obs)
    return TrainState(
        params=params,
        target_params=jax.tree_util.tree_map(jnp.copy, params),
        opt_state=optimizer.init(params),
        step=jnp.zeros((), jnp.int32),
        rng=rng,
    )


def build_train_step(
    network: nn.Module,
    optimizer: optax.GradientTransformation,
    loss_kind: str = "huber",
    huber_kappa: float = 1.0,
    target_sync_freq: int = 2500,
    use_is_weights: bool = True,
    priority_epsilon: float = 1e-6,
    jit: bool = True,
) -> Callable[[TrainState, PrioritizedBatch], Tuple[TrainState, StepMetrics]]:
    """Build the fused step.  All knobs are static — baked into the XLA program."""

    def loss_fn(params, target_params, batch: PrioritizedBatch):
        t = batch.transition
        B = t.action.shape[0]
        # One online forward over [obs; next_obs] (2B) instead of two B-sized
        # passes — bigger matmuls tile better on the MXU.
        q_both = network.apply(params, jnp.concatenate([t.obs, t.next_obs], axis=0))[2]
        q_values, q_next_online = q_both[:B], q_both[B:]
        q_next_target = network.apply(target_params, t.next_obs)[2]
        targets = losses.double_q_target(
            q_next_online, q_next_target, t.reward, t.discount
        )
        delta = losses.td_error(q_values, t.action, targets)
        weights = batch.is_weights if use_is_weights else None
        loss = losses.td_loss(delta, weights, kind=loss_kind, huber_kappa=huber_kappa)
        return loss, (delta, q_values)

    def train_step(state: TrainState, batch: PrioritizedBatch):
        (loss, (delta, q_values)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, state.target_params, batch)
        # When the batch is sharded over a data axis under pjit/shard_map, the
        # mean inside loss_fn makes XLA insert the gradient all-reduce over
        # ICI automatically — no explicit collective needed here.
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        step = state.step + 1
        # Intended target sync: copy exactly every target_sync_freq steps
        # (reference learner.py:60 inverts this gate).
        sync = (step % target_sync_freq) == 0
        new_target = jax.tree_util.tree_map(
            lambda online, target: jnp.where(sync, online, target),
            new_params,
            state.target_params,
        )
        metrics = StepMetrics(
            loss=loss,
            mean_abs_td=jnp.mean(jnp.abs(delta)),
            max_abs_td=jnp.max(jnp.abs(delta)),
            priorities=losses.priorities_from_td(delta, priority_epsilon),
            mean_q=jnp.mean(q_values),
        )
        new_state = TrainState(
            params=new_params,
            target_params=new_target,
            opt_state=new_opt_state,
            step=step,
            rng=state.rng,
        )
        return new_state, metrics

    if jit:
        return jax.jit(train_step, donate_argnums=(0,))
    return train_step
