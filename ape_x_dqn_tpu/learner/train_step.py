"""The fused learner step — one XLA program per gradient update.

This is the north-star fusion (BASELINE.json): everything the reference
learner does per update across four call sites and three host↔host RPCs
(reference learner.py:63-80 — sample unpack, double-Q target, TD error, loss,
RMSProp step, target-net sync, priority computation) compiles into a single
jitted function:

    train_step(state, batch) -> (new_state, StepMetrics)

Semantics implemented are the *intended* ones (SURVEY §2.8 defect register):
  * target net copies every ``target_sync_freq`` steps (the reference's modulo
    gate is inverted — learner.py:60);
  * per-transition priorities (the reference collapses them — learner.py:50);
  * terminal masking via the n-step discount (the reference bootstraps through
    episode ends);
  * RMSProp decay is decay, not L2 weight-decay (learner.py:26 misroutes it).

The returned function is pure and donation-friendly: ``state`` is donated so
params/opt-state update in place in HBM.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn
from flax import struct

from ape_x_dqn_tpu.ops import losses
from ape_x_dqn_tpu.types import PrioritizedBatch, TrainState

# Modern jax.shard_map tracks replication and its AD transpose psums param
# cotangents implicitly; the 0.4.x experimental fallback (see
# parallel.mesh.shard_map) does not — build_train_step's grad_reduce_axis
# branch keys on this (details at the branch).
_SHARD_MAP_IMPLICIT_GRAD_PSUM = hasattr(jax, "shard_map")


@struct.dataclass
class StepMetrics:
    loss: jax.Array            # float32 []
    mean_abs_td: jax.Array     # float32 []
    max_abs_td: jax.Array      # float32 []
    priorities: jax.Array      # float32 [B] — new replay priorities
    mean_q: jax.Array          # float32 []


def _scale_by_rms_lowp(
    decay: float, eps: float, second_moment_dtype
) -> optax.GradientTransformation:
    """``optax.scale_by_rms`` with the second-moment EMA stored in a reduced
    dtype (bfloat16 halves its HBM read+write per step — the optimizer is
    bandwidth-bound, ~91 µs/step measured for 3.4M params on a v5e).

    The EMA is *updated* in float32 (nu is upcast, blended, then stored back
    down) so the only loss is ~0.4% relative rounding on a statistic that is
    itself a noisy average — noise-level for RMSProp's denominator.
    """

    def init_fn(params):
        nu = jax.tree_util.tree_map(
            lambda p: jnp.zeros_like(p, dtype=second_moment_dtype), params
        )
        return optax.ScaleByRmsState(nu=nu)

    def update_fn(updates, state, params=None):
        del params
        nu32 = jax.tree_util.tree_map(
            lambda v: v.astype(jnp.float32), state.nu
        )
        nu32 = jax.tree_util.tree_map(
            lambda g, v: decay * v + (1.0 - decay) * jnp.square(g.astype(jnp.float32)),
            updates,
            nu32,
        )
        # Same formula as optax.scale_by_rms(eps_in_sqrt=True), its default
        # and what optax.rmsprop uses: g * rsqrt(nu + eps).
        scaled = jax.tree_util.tree_map(
            lambda g, v: (g.astype(jnp.float32) * jax.lax.rsqrt(v + eps)).astype(g.dtype),
            updates,
            nu32,
        )
        new_nu = jax.tree_util.tree_map(
            lambda v: v.astype(second_moment_dtype), nu32
        )
        return scaled, optax.ScaleByRmsState(nu=new_nu)

    return optax.GradientTransformation(init_fn, update_fn)


def with_float32_master(
    optimizer: optax.GradientTransformation,
) -> optax.GradientTransformation:
    """Mixed-precision wrapper: run ``optimizer`` against a float32 master
    copy of the params kept inside the optimizer state, while the network's
    own params live in bfloat16.

    Why: with bfloat16 params the per-step update (~lr · normalized-grad,
    ~6e-5) is below bfloat16's resolution at typical weight magnitudes, so
    naive ``apply_updates`` rounds most updates to zero and learning stalls.
    The master copy accumulates in float32; the emitted update is exactly
    the delta that lands the low-precision params on ``cast(master)`` (the
    add is lossless whenever params and master are within 2× of each other —
    Sterbenz — i.e. always, for a per-step change this small).

    HBM accounting (3.4M-param net, per step): forward/backward read params
    at half width (−13 MB and the f32→bf16 cast op disappears), while the
    optimizer carries the master r/w (+26 MB) but drops the f32 param r/w
    (−26 MB) — net ~−20 MB/step of a ~100 MB/step bandwidth-bound program.
    """

    def init_fn(params):
        master = jax.tree_util.tree_map(
            lambda p: p.astype(jnp.float32), params
        )
        return (master, optimizer.init(master))

    def update_fn(updates, state, params):
        master, inner = state
        g32 = jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32), updates
        )
        upd, inner = optimizer.update(g32, inner, master)
        new_master = optax.apply_updates(master, upd)
        emitted = jax.tree_util.tree_map(
            lambda m, p: m.astype(p.dtype) - p, new_master, params
        )
        return emitted, (new_master, inner)

    return optax.GradientTransformation(init_fn, update_fn)


def make_optimizer(
    kind: str = "rmsprop",
    learning_rate: float = 0.00025 / 4,
    rmsprop_decay: float = 0.95,
    rmsprop_eps: float = 1.5e-7,
    adam_b1: float = 0.9,
    adam_b2: float = 0.999,
    max_grad_norm: float | None = 40.0,
    second_moment_dtype=None,
) -> optax.GradientTransformation:
    """Reference-parity RMSProp (lr 0.00025/4, eps 1.5e-7 — learner.py:26,
    with decay routed correctly) or Adam, with optional grad clipping.

    ``second_moment_dtype=jnp.bfloat16`` (rmsprop only) stores the RMS EMA
    in bfloat16 — an HBM-traffic knob for the fused throughput path; the
    chain-MDP learning test covers this mode end-to-end.  ``max_grad_norm=
    None`` drops the global-norm clip (the reference has none — learner.py:26
    — and the clip costs an extra full pass over the gradients)."""
    if kind == "rmsprop":
        if second_moment_dtype is not None:
            opt = optax.chain(
                _scale_by_rms_lowp(rmsprop_decay, rmsprop_eps, second_moment_dtype),
                optax.scale(-learning_rate),
            )
        else:
            opt = optax.rmsprop(learning_rate, decay=rmsprop_decay, eps=rmsprop_eps)
    elif kind == "adam":
        if second_moment_dtype is not None:
            raise ValueError("second_moment_dtype is only supported for rmsprop")
        opt = optax.adam(learning_rate, b1=adam_b1, b2=adam_b2)
    else:
        raise ValueError(f"unknown optimizer kind: {kind}")
    if max_grad_norm is not None:
        opt = optax.chain(optax.clip_by_global_norm(max_grad_norm), opt)
    return opt


def init_train_state(
    network: nn.Module,
    optimizer: optax.GradientTransformation,
    rng: jax.Array,
    sample_obs: jax.Array,
    target_dtype=None,
) -> TrainState:
    """Initialize params/target/opt-state from one example observation batch.

    ``target_dtype=jnp.bfloat16`` stores the target net in bfloat16: it is
    only ever read for inference (the double-Q bootstrap), so the cast costs
    ~0.4% relative rounding on Q-targets while halving the target-params HBM
    read on every step.  Syncs cast online → target dtype."""
    params = network.init(rng, sample_obs)
    if target_dtype is None:
        target = jax.tree_util.tree_map(jnp.copy, params)
    else:
        # A no-op astype (param dtype == target_dtype, e.g. bf16 params +
        # bf16 target) returns the SAME array — params and target_params
        # would alias one buffer, and donating the TrainState then
        # double-donates it: the TPU runtime rejects the program with an
        # opaque INVALID_ARGUMENT (round-3's "bf16 params don't compile"
        # was exactly this).  Force a real copy on the no-op path.
        target = jax.tree_util.tree_map(
            lambda p: (
                jnp.copy(p) if p.dtype == target_dtype
                else p.astype(target_dtype)
            ),
            params,
        )
    return TrainState(
        params=params,
        target_params=target,
        opt_state=optimizer.init(params),
        step=jnp.zeros((), jnp.int32),
        rng=rng,
    )


def build_train_step(
    network: nn.Module,
    optimizer: optax.GradientTransformation,
    loss_kind: str = "huber",
    huber_kappa: float = 1.0,
    target_sync_freq: int = 2500,
    use_is_weights: bool = True,
    priority_epsilon: float = 1e-6,
    sync_in_step: bool = True,
    grad_reduce_axis: str | None = None,
    jit: bool = True,
) -> Callable[[TrainState, PrioritizedBatch], Tuple[TrainState, StepMetrics]]:
    """Build the fused step.  All knobs are static — baked into the XLA program.

    ``sync_in_step=False`` omits the per-step target-net sync: the target
    params pass through untouched and the caller syncs at its own cadence
    (the fused K-step scan hoists the sync to call boundaries — the per-step
    ``jnp.where`` tree-map rewrites the full target pytree in HBM every step,
    measured ~95 µs/step on a v5e for a 3.4M-param net, all wasted between
    the every-2500-step syncs).

    ``grad_reduce_axis``: set to a mesh axis name when the step runs inside
    ``shard_map`` with the batch sharded over that axis (the sharded fused
    learner, replay/device_dp.py) — gradients and scalar metrics all-reduce
    over it explicitly (``pmean``/``pmax`` over ICI), making the optimizer
    update identical on every shard.  Under plain ``jit``/pjit leave it
    ``None``: XLA's SPMD partitioner inserts the all-reduce itself from the
    batch sharding (parallel/dp.py).  Per-row priorities stay per-shard.
    """

    def loss_fn(params, target_params, batch: PrioritizedBatch):
        t = batch.transition
        B = t.action.shape[0]
        # One online forward over [obs; next_obs] (2B) instead of two B-sized
        # passes — bigger matmuls tile better on the MXU.
        q_both = network.apply(params, jnp.concatenate([t.obs, t.next_obs], axis=0))[2]
        q_values, q_next_online = q_both[:B], q_both[B:]
        q_next_target = network.apply(target_params, t.next_obs)[2]
        targets = losses.double_q_target(
            q_next_online, q_next_target, t.reward, t.discount
        )
        delta = losses.td_error(q_values, t.action, targets)
        weights = batch.is_weights if use_is_weights else None
        loss = losses.td_loss(delta, weights, kind=loss_kind, huber_kappa=huber_kappa)
        return loss, (delta, q_values)

    def train_step(state: TrainState, batch: PrioritizedBatch):
        (loss, (delta, q_values)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params, state.target_params, batch)
        # Under plain pjit the mean inside loss_fn makes XLA insert the
        # gradient all-reduce over ICI automatically.  Inside shard_map
        # (varying-axes AD semantics): the params enter unvarying while the
        # batch is varying, so jax's transpose ALREADY psums the param
        # cotangents over the axis — grads arrive as Σ_shards(local-mean
        # grads).  Dividing by the axis extent yields the global batch mean
        # (equal-size shards); an explicit pmean here would double-count
        # (measured: exactly n× updates).  The scalar loss is still
        # per-shard varying and needs a real pmean for reporting.
        #
        # On 0.4.x jax (the experimental shard_map via parallel.mesh's
        # compat wrapper, check_rep=False) there is NO replication tracking:
        # the transpose inserts no psum and grads arrive shard-LOCAL, so
        # the explicit pmean is the reduction — gated on the modern
        # spelling's presence, same predicate the wrapper dispatches on.
        if grad_reduce_axis is not None:
            if _SHARD_MAP_IMPLICIT_GRAD_PSUM:
                n_sh = jax.lax.psum(1, grad_reduce_axis)
                grads = jax.tree_util.tree_map(lambda g: g / n_sh, grads)
            else:
                grads = jax.lax.pmean(grads, grad_reduce_axis)
            loss = jax.lax.pmean(loss, grad_reduce_axis)
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        step = state.step + 1
        if sync_in_step:
            # Intended target sync: copy exactly every target_sync_freq steps
            # (reference learner.py:60 inverts this gate).
            sync = (step % target_sync_freq) == 0
            new_target = jax.tree_util.tree_map(
                lambda online, target: jnp.where(
                    sync, online.astype(target.dtype), target
                ),
                new_params,
                state.target_params,
            )
        else:
            new_target = state.target_params
        mean_abs_td = jnp.mean(jnp.abs(delta))
        max_abs_td = jnp.max(jnp.abs(delta))
        mean_q = jnp.mean(q_values)
        if grad_reduce_axis is not None:
            mean_abs_td = jax.lax.pmean(mean_abs_td, grad_reduce_axis)
            max_abs_td = jax.lax.pmax(max_abs_td, grad_reduce_axis)
            mean_q = jax.lax.pmean(mean_q, grad_reduce_axis)
        metrics = StepMetrics(
            loss=loss,
            mean_abs_td=mean_abs_td,
            max_abs_td=max_abs_td,
            priorities=losses.priorities_from_td(delta, priority_epsilon),
            mean_q=mean_q,
        )
        new_state = TrainState(
            params=new_params,
            target_params=new_target,
            opt_state=new_opt_state,
            step=step,
            rng=state.rng,
        )
        return new_state, metrics

    if jit:
        return jax.jit(train_step, donate_argnums=(0,))
    return train_step
