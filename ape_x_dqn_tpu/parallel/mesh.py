"""Device mesh + sharding utilities — the distributed backend's foundation.

The reference's "distributed backend" is Python ``multiprocessing`` on one
host (manager dict / queue / proxy RPC — reference main.py:18,37-42, SURVEY
§1 L4).  The TPU-native equivalent is laid out here per SURVEY §2's backend
entry: a ``jax.sharding.Mesh`` over the slice, parameters replicated, batches
sharded over the ``data`` axis, and XLA inserting the gradient all-reduce
over ICI — no hand-written collectives, no NCCL translation.

The mesh is 2D ``(data, model)`` by default with ``model=1``: data
parallelism is the capability the learner needs (BASELINE.md config 4), and
the ``model`` axis makes tensor-parallel layouts *expressible* (SURVEY §2
parallelism checklist: "design the param/pytree plumbing on NamedSharding so
TP is expressible") — ``infer_param_sharding`` shards wide dense kernels over
it when it has extent > 1.

Multi-host: all helpers operate on ``jax.devices()``, which under
``jax.distributed.initialize`` spans every host in the slice; shardings laid
out here put the all-reduce on ICI within a slice and DCN across slices
exactly as XLA's device assignment dictates — nothing below changes.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map(f, mesh: Mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: the top-level spelling landed
    after 0.4.x, where it lives at ``jax.experimental.shard_map.shard_map``
    (same semantics) — every shard_map call in the repo routes through here
    so the sharded fused paths run on both."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    # check_rep=False: the 0.4.x static replication checker can't see
    # through psum-producing bodies (the grad all-reduce) and rejects
    # replicated out_specs the newer checker accepts.
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def make_mesh(
    num_devices: Optional[int] = None,
    model_parallel: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``(data, model)`` mesh over the first ``num_devices`` devices.

    Args:
      num_devices: devices to use (default: all visible).
      model_parallel: extent of the ``model`` axis; must divide num_devices.
    """
    devs = list(devices if devices is not None else jax.devices())
    n = num_devices if num_devices is not None else len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, only {len(devs)} visible")
    if n % model_parallel != 0:
        raise ValueError(
            f"model_parallel={model_parallel} must divide num_devices={n}"
        )
    grid = np.array(devs[:n]).reshape(n // model_parallel, model_parallel)
    return Mesh(grid, ("data", "model"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading axis sharded over ``data``; all trailing axes replicated."""
    return NamedSharding(mesh, P("data"))


def tree_batch_sharding(tree, mesh: Mesh):
    """Batch sharding for every leaf of a batched pytree."""
    sh = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda _: sh, tree)


def infer_param_sharding(params, mesh: Mesh, min_dim: int = 512):
    """Tensor-parallel layout rule: shard the trailing dim of any kernel
    whose trailing dim is divisible by the ``model`` axis extent and at
    least ``min_dim``; replicate everything else.

    With ``model=1`` (the default mesh) this replicates every leaf — DP
    exactly.  With ``model>1`` the two 512-wide dueling-stream dense kernels
    and the 3136→512 projections shard over ``model``, demonstrating the
    full 2D layout on the same code path.
    """
    m = mesh.shape["model"]

    def rule(x):
        if (
            m > 1
            and hasattr(x, "ndim")
            and x.ndim >= 2
            and x.shape[-1] >= min_dim
            and x.shape[-1] % m == 0
        ):
            spec = [None] * (x.ndim - 1) + ["model"]
            return NamedSharding(mesh, P(*spec))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map(rule, params)


def shard_train_state(state, mesh: Mesh, min_dim: int = 512):
    """Sharding pytree for a TrainState: params/target/opt-state follow the
    param rule (optimizer moments mirror their parameters), scalars
    replicated."""
    param_sh = infer_param_sharding(state.params, mesh, min_dim)
    target_sh = infer_param_sharding(state.target_params, mesh, min_dim)

    # Optimizer state leaves mirror param shapes where they match; anything
    # else (counts, scalars) replicates.
    shape_map = {}
    for leaf, sh in zip(
        jax.tree_util.tree_leaves(state.params),
        jax.tree_util.tree_leaves(param_sh),
    ):
        shape_map.setdefault(getattr(leaf, "shape", ()), sh)

    rep = replicated(mesh)

    def opt_rule(x):
        return shape_map.get(getattr(x, "shape", ()), rep)

    opt_sh = jax.tree_util.tree_map(opt_rule, state.opt_state)
    return type(state)(
        params=param_sh,
        target_params=target_sh,
        opt_state=opt_sh,
        step=rep,
        rng=rep,
    )


def place_state(state, state_sharding):
    """Device-put a host train state onto the mesh per its sharding tree."""
    return jax.tree_util.tree_map(
        lambda x, sh: jax.device_put(x, sh), state, state_sharding
    )
