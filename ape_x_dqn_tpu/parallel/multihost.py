"""Multi-host (cross-process) execution — the DCN seam.

The reference's "distributed backend" never leaves one machine (Python
``multiprocessing`` manager primitives — reference main.py:18,37-42, SURVEY
§1 L4).  Here multi-host is the SPMD model TPU pods use: every host runs
the SAME program, ``jax.distributed.initialize`` stitches their local
devices into one global device set, the mesh helpers (parallel/mesh.py)
already operate on ``jax.devices()`` — which is now global — and XLA routes
collectives over ICI within a host's slice and DCN between hosts.  The
sharded train step (parallel/dp.py) needs NO changes: the data-parallel
gradient all-reduce simply spans processes.

Verified in this tree without TPU pod hardware via the CPU backend: two OS
processes × 4 virtual devices each form one 8-device global mesh and train
with identical replicated losses (tests/test_multihost.py) — the same
wiring a v4 pod uses, with gloo/gRPC standing in for ICI/DCN.

Division of labor per host in the full Ape-X layout:
  * every host runs the learner program (SPMD) over the global mesh;
  * each host's actor fleets feed its LOCAL replay shard, and each host
    samples learner batches from its local replay — batch rows are
    host-local, which is exactly what a ``data``-axis sharding wants
    (rows land on the host's own devices; no cross-host batch traffic);
  * priorities come back data-sharded: each host restamps its own rows
    (``local_shard``);
  * params are replicated by construction — publication to that host's
    actors is a local ``device_get`` (the ParamStore seam, serialized
    snapshots over runtime/process_actors.py transports).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def initialize_multihost(
    coordinator: str,
    num_processes: int,
    process_id: int,
    local_device_ids: Optional[list] = None,
) -> None:
    """``jax.distributed.initialize`` with the framework's conventions.

    Call BEFORE any other jax API touches the backend.  After this,
    ``jax.devices()`` is the global device set and ``parallel.make_mesh()``
    builds the global mesh.
    """
    import jax

    kwargs = {}
    if local_device_ids is not None:
        kwargs["local_device_ids"] = local_device_ids
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
        **kwargs,
    )


def host_value(arr) -> np.ndarray:
    """Host numpy view of a REPLICATED global array (loss, step counters):
    every process holds a full copy, so read the first addressable shard —
    ``np.asarray`` on a non-fully-addressable array raises."""
    return np.asarray(arr.addressable_data(0))


def local_shard(arr) -> np.ndarray:
    """This process's rows of a data-sharded global array (priorities), in
    GLOBAL row order — the rows this host's replay owns.

    ``addressable_shards`` is ordered by device assignment, which need not
    match row order (non-contiguous local device ids on a pod slice), so
    sort by each shard's global index before concatenating — otherwise a
    priority could restamp the wrong replay row."""
    shards = sorted(
        arr.addressable_shards,
        key=lambda s: s.index[0].start if s.index and s.index[0].start else 0,
    )
    return np.concatenate([np.asarray(s.data) for s in shards], axis=0)


def barrier(name: str) -> None:
    """Cross-host synchronization point (no-op single-process).  Used to
    order multi-host checkpoint writes: every host's replay shard must be
    on disk BEFORE process 0 commits the state dir that marks the
    checkpoint as restorable."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def process_count() -> int:
    import jax

    return jax.process_count()


def process_index() -> int:
    import jax

    return jax.process_index()
