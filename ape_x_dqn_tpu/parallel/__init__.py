"""Parallelism layer: device mesh, shardings, data-parallel learner step."""

from ape_x_dqn_tpu.parallel.dp import build_sharded_train_step, place_batch
from ape_x_dqn_tpu.parallel.mesh import (
    batch_sharding,
    infer_param_sharding,
    make_mesh,
    place_state,
    replicated,
    shard_train_state,
    tree_batch_sharding,
)

__all__ = [
    "batch_sharding",
    "build_sharded_train_step",
    "infer_param_sharding",
    "make_mesh",
    "place_batch",
    "place_state",
    "replicated",
    "shard_train_state",
    "tree_batch_sharding",
]
