"""Parallelism layer: device mesh, shardings, data-parallel learner step,
multi-host initialization."""

from ape_x_dqn_tpu.parallel.dp import build_sharded_train_step, place_batch
from ape_x_dqn_tpu.parallel.multihost import (
    host_value,
    initialize_multihost,
    local_shard,
)
from ape_x_dqn_tpu.parallel.mesh import (
    batch_sharding,
    infer_param_sharding,
    make_mesh,
    place_state,
    replicated,
    shard_train_state,
    tree_batch_sharding,
)

__all__ = [
    "batch_sharding",
    "build_sharded_train_step",
    "host_value",
    "infer_param_sharding",
    "initialize_multihost",
    "local_shard",
    "make_mesh",
    "place_batch",
    "place_state",
    "replicated",
    "shard_train_state",
    "tree_batch_sharding",
]
