"""The sharded learner step: one XLA program over the whole mesh.

``build_sharded_train_step`` takes the same fused train step the single-chip
learner uses (learner/train_step.py — double-Q target, loss, grads, optimizer,
target sync, priorities in one program) and jits it with mesh shardings:

  * TrainState replicated (or model-axis sharded for wide kernels —
    parallel/mesh.py);
  * the replay batch sharded over ``data`` on its leading axis;
  * XLA's SPMD partitioner turns the batch-mean loss gradient into partial
    per-shard reductions + an **all-reduce over ICI** — the TPU-native
    replacement for the learner data-parallelism the reference entirely
    lacks (single CPU learner process, SURVEY §2 parallelism checklist);
  * per-transition priorities come back sharded over ``data``; the host
    gathers them when writing to the replay (a [B] float vector — trivial
    DCN/PCIe traffic).

This is BASELINE.md config 4 ("Data-parallel learner on v4-8: pjit grad
all-reduce over ICI") as a library function.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ape_x_dqn_tpu.learner.train_step import StepMetrics, build_train_step
from ape_x_dqn_tpu.parallel.mesh import (
    batch_sharding,
    place_state,
    replicated,
    shard_train_state,
    tree_batch_sharding,
)
from ape_x_dqn_tpu.types import PrioritizedBatch, TrainState


def build_sharded_train_step(
    network,
    optimizer,
    mesh: Mesh,
    state_example: TrainState,
    batch_example: PrioritizedBatch,
    **train_kwargs,
) -> Tuple[Callable, TrainState]:
    """Build the mesh-sharded fused step and place the state on the mesh.

    Returns ``(step_fn, sharded_state)``.  ``step_fn(state, batch) ->
    (state, metrics)`` donates the state; callers must feed batches placed
    with :func:`place_batch` (or any committed layout matching the batch
    sharding — jit moves uncommitted host arrays automatically).
    """
    base_step = build_train_step(network, optimizer, jit=False, **train_kwargs)

    state_sh = shard_train_state(state_example, mesh)
    batch_sh = tree_batch_sharding(batch_example, mesh)
    rep = replicated(mesh)
    metrics_sh = StepMetrics(
        loss=rep,
        mean_abs_td=rep,
        max_abs_td=rep,
        # Priorities stay data-sharded: each shard computed its own rows.
        priorities=NamedSharding(mesh, P("data")),
        mean_q=rep,
    )
    step_fn = jax.jit(
        base_step,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, metrics_sh),
        donate_argnums=(0,),
    )
    sharded_state = place_state(state_example, state_sh)
    return step_fn, sharded_state


def place_batch(batch: PrioritizedBatch, mesh: Mesh) -> PrioritizedBatch:
    """Shard a host batch over the mesh's data axis (leading dim).

    Single-process spelling: the caller holds the FULL batch.  Multi-host
    SPMD uses :func:`place_local_batch` (each process holds only its rows).
    """
    sh = batch_sharding(mesh)
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)


def place_local_batch(local_batch: PrioritizedBatch, mesh: Mesh) -> PrioritizedBatch:
    """Assemble the GLOBAL data-sharded batch from per-process local rows.

    Multi-host: every process passes its own ``B / process_count`` rows
    (sampled from its local replay); ``make_array_from_process_local_data``
    lays each process's rows onto its addressable shards, so global row
    order is process order — the inverse of ``multihost.local_shard``,
    which is what makes the per-host priority writeback line up with the
    per-host sample indices.
    """
    import numpy as np

    sh = batch_sharding(mesh)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(sh, np.asarray(x)),
        local_batch,
    )
