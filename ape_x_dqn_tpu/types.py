"""Transition schema and train-state pytrees.

The reference duplicates two namedtuples (``Transition``/``N_Step_Transition``)
by copy-paste across three files (reference: actor.py:11-12, learner.py:8,
replay.py:5).  Here the wire format is a single set of ``flax.struct`` pytrees
shared by every subsystem, so they move through ``jit``/``pjit`` and across
host threads without conversion.  There is deliberately no 1-step transition
type: the actor pool composes n-step windows from its history ring and only
``NStepTransition`` ever crosses a subsystem boundary.

Design notes (TPU-first):
  * Observations are stored ``uint8`` end-to-end and cast to compute dtype
    only inside the jitted step — HBM bandwidth and replay RAM are the
    bottleneck, not FLOPs.
  * Replay identity is an integer slot index, not the reference's string key
    ``str(actor_id)+str(seq_num)`` (reference: actor.py:47) — string keys force
    O(N) scans (reference: replay.py:54-56); indices make priority updates
    O(log N) in the sum-tree.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

Array = jax.Array
PyTree = Any


@struct.dataclass
class NStepTransition:
    """An n-step transition (reference actor.py:12 ``N_Step_Transition``).

    ``reward`` is the accumulated n-step return R_{t→t+n}; ``discount`` is the
    *correct* bootstrap factor γ^n with terminal masking (the reference stores
    γ^(n−1) and never masks — SURVEY §2.8), so the learner target is simply
    ``reward + discount * bootstrap`` with no special cases.
    """

    obs: Array          # uint8 [*obs_shape]        — S_t
    action: Array       # int32 []                  — A_t
    reward: Array       # float32 []                — R_{t→t+n}
    discount: Array     # float32 []                — prod_k γ·(1−done_k), 0 past terminal
    next_obs: Array     # uint8 [*obs_shape]        — S_{t+n}

    @property
    def batch_shape(self):
        return self.action.shape


class DedupChunk(NamedTuple):
    """An actor flush with each frame stored ONCE — the frame-dedup wire
    format (round-4 verdict item 1a: the double-store's ``obs`` +
    ``next_obs`` is a 2× tax on RAM, ingest bandwidth, snapshots and HBM).

    ``frames`` holds the flush's unique observations; each transition
    references its S_t / S_{t+n} by index.  Refs are relative to THIS
    chunk's first frame: ``r >= 0`` → ``frames[r]``; ``r < 0`` → frame
    ``prev_frames + r`` of this source's PREVIOUS chunk (the n-row overlap
    between consecutive sliding windows — consecutive chunks share their
    boundary frames, so steady-state frame traffic is ~1 frame per
    transition instead of 2).  Consumers resolve refs against a per-source
    frame counter; a gap in ``chunk_seq`` (dropped/reordered chunk, worker
    respawn) invalidates carry refs, and consumers drop just the carried
    rows (≤ n·num_actors once per gap).

    Layout contract (producers): frames are ordered [step-row-major, then
    truncation extras]; ``obs_ref < next_ref`` row-wise (liveness checks
    use ``obs_ref`` as each row's oldest frame).
    """

    frames: np.ndarray     # uint8 [U, *obs_shape] — each unique frame once
    obs_ref: np.ndarray    # int32 [M] — S_t ref (may be negative: carry)
    next_ref: np.ndarray   # int32 [M] — S_{t+n} ref (>= 0 always)
    action: np.ndarray     # int32 [M]
    reward: np.ndarray     # float32 [M] — n-step return
    discount: np.ndarray   # float32 [M] — bootstrap factor
    source: int            # producer identity (fresh per fleet incarnation)
    chunk_seq: int         # per-source monotone flush counter
    prev_frames: int       # U of this source's previous chunk (carry check)

    @property
    def batch_shape(self):
        return self.action.shape


def materialize_dedup(chunk: DedupChunk, prev: DedupChunk | None = None):
    """Decode a DedupChunk (plus its predecessor, for carry refs) back to a
    dense NStepTransition — the test oracle for emission equivalence and
    the fallback for consumers that want the dense wire format."""
    neg = chunk.obs_ref < 0
    if neg.any():
        if prev is None:
            raise ValueError("chunk has carry refs but no previous chunk")
        if prev.frames.shape[0] != chunk.prev_frames:
            raise ValueError("previous chunk size mismatch for carry refs")
        carry_idx = np.clip(chunk.prev_frames + chunk.obs_ref,
                            0, chunk.prev_frames - 1)
        obs = np.where(
            neg[(...,) + (None,) * (chunk.frames.ndim - 1)],
            prev.frames[carry_idx],
            chunk.frames[np.clip(chunk.obs_ref, 0, None)],
        )
    else:
        obs = chunk.frames[chunk.obs_ref]
    return NStepTransition(
        obs=obs,
        action=chunk.action,
        reward=chunk.reward,
        discount=chunk.discount,
        next_obs=chunk.frames[chunk.next_ref],
    )


@struct.dataclass
class PrioritizedBatch:
    """A replay sample as fed to the learner: transitions + sampling metadata."""

    transition: NStepTransition
    indices: Array      # int32 [B] — replay slot ids, echoed back for priority update
    is_weights: Array   # float32 [B] — importance-sampling weights (β-annealed)


@struct.dataclass
class TrainState:
    """Full learner state: one pytree, one checkpoint, one donation unit.

    Covers everything the reference fails to checkpoint (reference
    learner.py:18-23 restores only the online net): params, target params,
    optimizer state, step counter and PRNG key.
    """

    params: PyTree
    target_params: PyTree
    opt_state: PyTree
    step: Array         # int32 []
    rng: Array          # PRNGKey


def host_stack(transitions):
    """Stack a list of same-structure pytrees into one batched pytree (numpy).

    Host-side helper for the actor→replay path; stays off the device.
    """
    leaves = [jax.tree_util.tree_leaves(t) for t in transitions]
    treedef = jax.tree_util.tree_structure(transitions[0])
    stacked = [np.stack([l[i] for l in leaves]) for i in range(len(leaves[0]))]
    return jax.tree_util.tree_unflatten(treedef, stacked)


def tree_slice(tree: PyTree, idx) -> PyTree:
    """Index every leaf of a batched pytree (host or device)."""
    return jax.tree_util.tree_map(lambda x: x[idx], tree)
