"""Transition schema and train-state pytrees.

The reference duplicates two namedtuples (``Transition``/``N_Step_Transition``)
by copy-paste across three files (reference: actor.py:11-12, learner.py:8,
replay.py:5).  Here the wire format is a single set of ``flax.struct`` pytrees
shared by every subsystem, so they move through ``jit``/``pjit`` and across
host threads without conversion.  There is deliberately no 1-step transition
type: the actor pool composes n-step windows from its history ring and only
``NStepTransition`` ever crosses a subsystem boundary.

Design notes (TPU-first):
  * Observations are stored ``uint8`` end-to-end and cast to compute dtype
    only inside the jitted step — HBM bandwidth and replay RAM are the
    bottleneck, not FLOPs.
  * Replay identity is an integer slot index, not the reference's string key
    ``str(actor_id)+str(seq_num)`` (reference: actor.py:47) — string keys force
    O(N) scans (reference: replay.py:54-56); indices make priority updates
    O(log N) in the sum-tree.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

Array = jax.Array
PyTree = Any


@struct.dataclass
class NStepTransition:
    """An n-step transition (reference actor.py:12 ``N_Step_Transition``).

    ``reward`` is the accumulated n-step return R_{t→t+n}; ``discount`` is the
    *correct* bootstrap factor γ^n with terminal masking (the reference stores
    γ^(n−1) and never masks — SURVEY §2.8), so the learner target is simply
    ``reward + discount * bootstrap`` with no special cases.
    """

    obs: Array          # uint8 [*obs_shape]        — S_t
    action: Array       # int32 []                  — A_t
    reward: Array       # float32 []                — R_{t→t+n}
    discount: Array     # float32 []                — prod_k γ·(1−done_k), 0 past terminal
    next_obs: Array     # uint8 [*obs_shape]        — S_{t+n}

    @property
    def batch_shape(self):
        return self.action.shape


@struct.dataclass
class PrioritizedBatch:
    """A replay sample as fed to the learner: transitions + sampling metadata."""

    transition: NStepTransition
    indices: Array      # int32 [B] — replay slot ids, echoed back for priority update
    is_weights: Array   # float32 [B] — importance-sampling weights (β-annealed)


@struct.dataclass
class TrainState:
    """Full learner state: one pytree, one checkpoint, one donation unit.

    Covers everything the reference fails to checkpoint (reference
    learner.py:18-23 restores only the online net): params, target params,
    optimizer state, step counter and PRNG key.
    """

    params: PyTree
    target_params: PyTree
    opt_state: PyTree
    step: Array         # int32 []
    rng: Array          # PRNGKey


def host_stack(transitions):
    """Stack a list of same-structure pytrees into one batched pytree (numpy).

    Host-side helper for the actor→replay path; stays off the device.
    """
    leaves = [jax.tree_util.tree_leaves(t) for t in transitions]
    treedef = jax.tree_util.tree_structure(transitions[0])
    stacked = [np.stack([l[i] for l in leaves]) for i in range(len(leaves[0]))]
    return jax.tree_util.tree_unflatten(treedef, stacked)


def tree_slice(tree: PyTree, idx) -> PyTree:
    """Index every leaf of a batched pytree (host or device)."""
    return jax.tree_util.tree_map(lambda x: x[idx], tree)
