"""On-demand ``jax.profiler`` capture, triggered from /varz?trace=1.

The ROADMAP's open profiler item (the 4.5k→12.5k steps/s gap) has no
committed trace partly because capturing one meant stopping the run and
re-launching ``tools/trace_capture.py`` under the right config.  This
hook removes that step: hit ``/varz?trace=1`` on a LIVE trainer and a
background thread traces the next N learner steps into a TensorBoard
logdir, then tries to parse the xplane protobuf into the same op-level
JSON summary ``tools/trace_capture.py`` produces (its ``summarize_xplane``
is loaded by file path — ``tools/`` is not a package — and skipped
gracefully when tensorflow isn't importable).

Platform discipline is inherited from ``utils/profiling.trace``: where
the profiler plugin can't trace (the tunneled TPU), the capture degrades
to a recorded no-op — hitting the endpoint must never kill a run.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from typing import Callable, Optional


def _load_summarizer():
    """``tools/trace_capture.summarize_xplane`` by file path, or None —
    the tools tree may be absent in an installed package, and its
    tensorflow import is too heavy to pay at module scope."""
    try:
        import importlib.util

        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        path = os.path.join(root, "tools", "trace_capture.py")
        if not os.path.exists(path):
            return None
        spec = importlib.util.spec_from_file_location("_trace_capture", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.summarize_xplane
    except Exception:  # noqa: BLE001 — summary is best-effort garnish
        return None


class TraceOnDemand:
    """One in-flight capture at a time; ``trigger()`` returns immediately
    with a status dict (the /varz reply), the capture thread does the
    waiting."""

    def __init__(self, step_fn: Optional[Callable[[], int]] = None,
                 steps: int = 512, out_dir: Optional[str] = None,
                 timeout_s: float = 60.0):
        self._step_fn = step_fn
        self._steps = int(steps)
        self._out_dir = out_dir
        self._timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._busy = False
        self.last: dict = {"state": "idle"}

    def trigger(self, steps: Optional[int] = None) -> dict:
        with self._lock:
            if self._busy:
                return {"state": "already-running", **self.last}
            self._busy = True
        n = int(steps) if steps else self._steps
        logdir = self._out_dir or tempfile.mkdtemp(prefix="obs_trace_")
        self.last = {"state": "capturing", "logdir": logdir, "steps": n}
        threading.Thread(
            target=self._capture, args=(logdir, n),
            name="obs-trace-capture", daemon=True,
        ).start()
        return dict(self.last)

    def status(self) -> dict:
        return dict(self.last)

    def _capture(self, logdir: str, n: int) -> None:
        from ape_x_dqn_tpu.utils.profiling import trace

        rec = {"logdir": logdir, "steps_requested": n}
        try:
            start = self._step_fn() if self._step_fn else 0
            deadline = time.monotonic() + self._timeout_s
            t0 = time.monotonic()
            with trace(logdir) as started:
                rec["trace_started"] = bool(started)
                if self._step_fn is not None:
                    while (self._step_fn() < start + n
                           and time.monotonic() < deadline):
                        time.sleep(0.05)
                    rec["steps_traced"] = self._step_fn() - start
                else:
                    time.sleep(min(2.0, self._timeout_s))
            rec["wall_s"] = round(time.monotonic() - t0, 3)
            if rec["trace_started"]:
                summarize = _load_summarizer()
                if summarize is not None:
                    try:
                        rec["summary"] = summarize(logdir)
                    except Exception as e:  # noqa: BLE001 — best-effort
                        rec["summary"] = {
                            "error": f"{type(e).__name__}: {e}"
                        }
                try:
                    with open(os.path.join(logdir, "summary.json"),
                              "w") as f:
                        json.dump(rec, f, default=str)
                except OSError:
                    pass
                rec["state"] = "done"
            else:
                # The utils/profiling.trace degraded path: the platform's
                # profiler can't trace — recorded, not raised.
                rec["state"] = "unavailable"
        except Exception as e:  # noqa: BLE001 — must never kill the run
            rec["state"] = "error"
            rec["reason"] = f"{type(e).__name__}: {e}"
        finally:
            self.last = rec
            with self._lock:
                self._busy = False
