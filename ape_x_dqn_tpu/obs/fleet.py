"""Fleet-wide observability plane: the rollup aggregator + SLO engine.

PR 4 gave every process its own /varz; PRs 8-12 multiplied the processes
— replay shards, serving replicas, remote worker hosts, N learners — and
the only "fleet view" left was an operator eyeballing N ports.  Horgan
et al. 2018 tune Ape-X by exactly the signals no single process can see
(age of experience across the fleet, replay throughput, actor/learner
balance), and ROADMAP item 3's elastic autopilot needs those signals as
INPUTS.  This module is that sensor layer:

  * :class:`FleetAggregator` — discovers every endpoint in a run (the
    trainer's /varz, replay shards via the fleet's endpoints file +
    their ``stats`` RPC, serving replicas via their ``obs_exporter``
    announcements, remote hosts), scrapes them on a cadence, and merges
    the per-process numbers with the same arithmetic the in-process
    ``merge()`` primitives use (histograms bucket-wise —
    ``utils.metrics.merge_bucket_dicts`` is the serialized twin of
    ``LatencyHistogram.merge`` — counters by sum, gauges by max).  The
    rollup serves its own ``/varz`` + ``/metrics`` + ``/healthz``: one
    dead scrape marks THAT endpoint down (``scrape_failures``) and the
    fleet view keeps serving — a half-dead fleet is exactly when the
    rollup matters most, so it never 503s on a member's death.
  * **SLO engine** (:class:`SloEngine`) — declarative rules over the
    rollup (age-of-experience p95 bound, inference rtt p99, serving
    p99 / QPS floor, ring-occupancy band, endpoint liveness) evaluated
    on burn-rate windows: a rule breaches only when the breaching
    fraction of the window crosses ``burn_threshold`` and clears only
    when it falls under ``clear_threshold`` — the hysteresis gap plus a
    minimum sample count damps flapping.  Transitions emit typed
    ``slo_breach`` / ``slo_clear`` JSONL events — the exact signals the
    autopilot (ROADMAP item 3) will actuate on.
  * **Trace timelines** — each scraped snapshot's recent cross-tier
    spans (``TraceSpanLog`` surfaces: the trainer's ``trace_spans``
    provider, a shard's ``stats`` RPC, a replica's ``serving_net``)
    group by trace id into end-to-end timelines: one experience
    worker → wire → shard add → learner sample → priority write-back,
    one inference request worker → router → replica → batcher → reply,
    with true cross-process hop latencies (CLOCK_MONOTONIC, one host).

Import-light by contract (stdlib at module scope, enforced by apexlint):
the aggregator is an operator tool that must come up in milliseconds on
any host that can reach the ports — the shard stats RPC client is the
one lazy import, and it is numpy-only.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
import zlib
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Callable, Dict, List, Optional, Tuple

from ape_x_dqn_tpu.utils.metrics import (
    bucket_percentile,
    emit_event,
    merge_bucket_dicts,
    merge_counter_maps,
    stamp_record,
)

# Shard counter keys the rollup sums across the fleet (a curated subset:
# summing everything would add port numbers).
_SHARD_SUM_KEYS = (
    "requests", "replies", "errors", "torn_frames", "bad_hellos",
    "stale_rejects", "add_dups", "chaos_dropped", "bytes_in", "bytes_out",
    "logical_bytes_in", "size", "capacity", "total_added", "saves",
)
_MAX_TRACES = 256      # trace ids kept for timeline assembly (LRU)
_ROLLUP_TRACES = 8     # newest multi-process timelines on the rollup


# ---------------------------------------------------------------------------
# SLO engine.
# ---------------------------------------------------------------------------


class SloRule:
    """One declarative bound over the rollup.

    ``kind`` is the direction: ``"upper"`` breaches while value > bound
    (latency/occupancy ceilings), ``"lower"`` while value < bound (QPS /
    liveness floors).  ``value_fn(rollup)`` extracts the measured value
    — None means "not measurable this sweep" and the sample is skipped
    (an absent metric is not a breach; endpoint liveness has its own
    rule)."""

    def __init__(self, name: str, kind: str, bound: float,
                 value_fn: Callable[[dict], Optional[float]]):
        if kind not in ("upper", "lower"):
            raise ValueError(f"unknown slo rule kind: {kind}")
        self.name = name
        self.kind = kind
        self.bound = float(bound)
        self.value_fn = value_fn
        self.state = "ok"              # "ok" | "breach"
        self.breaches = 0
        self.clears = 0
        self.last_value: Optional[float] = None
        self._window: deque = deque()  # (t, breached_bool)

    def violated(self, value: float) -> bool:
        return value > self.bound if self.kind == "upper" \
            else value < self.bound


class SloEngine:
    """Burn-rate evaluation of :class:`SloRule` s with flap damping.

    Each sweep appends one (t, violated) sample per rule; the breaching
    FRACTION of the trailing ``window_s`` is the burn rate.  ok→breach
    fires at ``burn >= burn_threshold``; breach→ok at ``burn <=
    clear_threshold`` — and because clear < burn there is a hysteresis
    band where the state HOLDS, so a metric oscillating around the bound
    cannot flap the alarm at sweep cadence.  ``min_samples`` gates both
    transitions (one bad scrape is not a breach; one good one is not a
    recovery)."""

    def __init__(self, rules: List[SloRule], *, window_s: float = 30.0,
                 burn_threshold: float = 0.5, clear_threshold: float = 0.1,
                 min_samples: int = 3, emit=None):
        if not 0.0 <= clear_threshold <= burn_threshold <= 1.0:
            raise ValueError(
                "slo thresholds must satisfy 0 <= clear <= burn <= 1"
            )
        self.rules = list(rules)
        self.window_s = float(window_s)
        self.burn_threshold = float(burn_threshold)
        self.clear_threshold = float(clear_threshold)
        self.min_samples = int(min_samples)
        self._emit = emit              # callable(event_name, **fields)
        self._subscribers: List[Callable] = []
        self.breaches = 0
        self.clears = 0

    def subscribe(self, fn: Callable[..., None]) -> None:
        """Register an event listener called as ``fn(name, **fields)`` on
        every ``slo_breach``/``slo_clear`` in addition to the emit sink —
        the actuation hook the autopilot controller consumes (a listener
        that raises is isolated; evaluation never stops)."""
        self._subscribers.append(fn)

    def _event(self, name: str, **fields) -> None:
        for fn in ((self._emit,) if self._emit is not None else ()) \
                + tuple(self._subscribers):
            try:
                fn(name, **fields)
            except Exception:  # noqa: BLE001 — telemetry must not stop evaluation
                pass

    def evaluate(self, rollup: dict, now: Optional[float] = None) -> dict:
        """One sweep over every rule; returns the ``slo`` status section
        and emits ``slo_breach`` / ``slo_clear`` on state transitions."""
        now = time.monotonic() if now is None else float(now)
        for rule in self.rules:
            try:
                value = rule.value_fn(rollup)
            except Exception:  # noqa: BLE001 — a broken extractor is "unmeasurable", not a crash
                value = None
            if value is None:
                rule.last_value = None
                continue
            value = float(value)
            rule.last_value = value
            rule._window.append((now, rule.violated(value)))
            cutoff = now - self.window_s
            while rule._window and rule._window[0][0] < cutoff:
                rule._window.popleft()
            n = len(rule._window)
            if n < self.min_samples:
                continue
            burn = sum(1 for _, v in rule._window if v) / n
            if rule.state == "ok" and burn >= self.burn_threshold:
                rule.state = "breach"
                rule.breaches += 1
                self.breaches += 1
                self._event(
                    "slo_breach", rule=rule.name, kind=rule.kind,
                    value=round(value, 4), bound=rule.bound,
                    burn=round(burn, 3), window_s=self.window_s,
                    samples=n,
                )
            elif rule.state == "breach" and burn <= self.clear_threshold:
                rule.state = "ok"
                rule.clears += 1
                self.clears += 1
                self._event(
                    "slo_clear", rule=rule.name, kind=rule.kind,
                    value=round(value, 4), bound=rule.bound,
                    burn=round(burn, 3), window_s=self.window_s,
                    samples=n,
                )
        return self.status()

    def status(self) -> dict:
        """The ``slo`` rollup section (docs/METRICS.md)."""
        rules = {}
        for rule in self.rules:
            w = rule._window
            burn = (sum(1 for _, v in w if v) / len(w)) if w else 0.0
            rules[rule.name] = {
                "state": rule.state,
                "kind": rule.kind,
                "bound": rule.bound,
                "value": (round(rule.last_value, 4)
                          if rule.last_value is not None else None),
                "burn": round(burn, 3),
                "samples": len(w),
                "breaches": rule.breaches,
                "clears": rule.clears,
            }
        return {
            "rules": rules,
            "breaching": sorted(r.name for r in self.rules
                                if r.state == "breach"),
            "breaches": self.breaches,
            "clears": self.clears,
            "window_s": self.window_s,
            "burn_threshold": self.burn_threshold,
            "clear_threshold": self.clear_threshold,
        }


class _BucketWindow:
    """Windowed percentiles over CUMULATIVE bucket dicts.

    The lineage age histogram and the replica latency histograms are
    cumulative for their process's lifetime, so their percentiles barely
    move once a run has history — a capacity action that fixed the
    CURRENT distribution would never show on them.  Feed each sweep's
    merged cumulative buckets here: per-edge deltas vs the previous feed
    accumulate in a trailing deque, and ``percentile`` re-derives from
    the window's summed deltas — the distribution of the LAST
    ``window_s`` seconds only.  Negative deltas (an endpoint respawned
    and its counters reset, or dropped out of the merge) clamp to zero:
    a reset loses at most one endpoint's window contribution, never
    corrupts the sum."""

    def __init__(self, window_s: float = 30.0):
        self.window_s = float(window_s)
        self._prev: dict = {}
        self._deltas: deque = deque()   # (t, {edge: count_delta})

    def feed(self, buckets: dict, now: float) -> None:
        delta = {
            k: max(0, int(v) - int(self._prev.get(k, 0)))
            for k, v in (buckets or {}).items()
        }
        self._prev = dict(buckets or {})
        if any(delta.values()):
            self._deltas.append((now, delta))
        cutoff = now - self.window_s
        while self._deltas and self._deltas[0][0] < cutoff:
            self._deltas.popleft()

    def merged(self) -> dict:
        out: dict = {}
        for _, d in self._deltas:
            out = merge_bucket_dicts(out, d)
        return out

    def count(self) -> int:
        return sum(sum(d.values()) for _, d in self._deltas)

    def percentile(self, q: float) -> Optional[float]:
        m = self.merged()
        if not any(m.values()):
            return None
        return bucket_percentile(m, q)


# -- rollup metric extractors (the rule vocabulary) -------------------------


def _age_p95_ms(rollup: dict) -> Optional[float]:
    age = rollup.get("age_of_experience") or {}
    win = age.get("window") or {}
    if win.get("count"):
        # Windowed value when the aggregator computes one: the SLO must
        # see the CURRENT distribution, not the run's whole history.
        return win.get("p95_s", 0.0) * 1e3
    if not age.get("count"):
        return None
    return age.get("p95_s", 0.0) * 1e3


def _inference_rtt_p99_ms(rollup: dict) -> Optional[float]:
    inf = rollup.get("inference") or {}
    return inf.get("rtt_p99_ms_max")


def _serving_p99_ms(rollup: dict) -> Optional[float]:
    srv = rollup.get("serving") or {}
    win = srv.get("window") or {}
    if win.get("count"):
        return win.get("p99_ms")
    if not srv.get("count"):
        return None
    return srv.get("p99_ms")


def _serving_qps(rollup: dict) -> Optional[float]:
    srv = rollup.get("serving") or {}
    if not srv.get("replicas"):
        return None
    return srv.get("qps", 0.0)


def _ring_occupancy(rollup: dict) -> Optional[float]:
    return rollup.get("ring_occupancy_max")


def _replay_add_qps_per_shard(rollup: dict) -> Optional[float]:
    """Fleet replay ingest pressure NORMALIZED per live shard — the
    signal that stays comparable across reshards: growing the fleet
    lowers it, shrinking raises it, so one bound governs both ends."""
    rep = rollup.get("replay") or {}
    shards = int(rep.get("shards_alive") or 0)
    if shards <= 0:
        return None
    return float(rep.get("add_qps") or 0.0) / shards


def _endpoints_down(rollup: dict) -> Optional[float]:
    eps = rollup.get("endpoints") or {}
    if not eps:
        return None
    return float(sum(1 for e in eps.values() if not e.get("alive")))


def rules_from_config(obs_cfg) -> List[SloRule]:
    """The config-declared rule set (``obs.fleet_slo_*``): a bound of 0
    (or an occupancy band of (0, 1]) leaves that rule off, so the default
    config evaluates only endpoint liveness."""
    rules: List[SloRule] = []
    if obs_cfg.fleet_slo_age_p95_ms > 0:
        rules.append(SloRule("age_p95_ms", "upper",
                             obs_cfg.fleet_slo_age_p95_ms, _age_p95_ms))
    if obs_cfg.fleet_slo_inference_rtt_p99_ms > 0:
        rules.append(SloRule(
            "inference_rtt_p99_ms", "upper",
            obs_cfg.fleet_slo_inference_rtt_p99_ms, _inference_rtt_p99_ms))
    if obs_cfg.fleet_slo_serving_p99_ms > 0:
        rules.append(SloRule("serving_p99_ms", "upper",
                             obs_cfg.fleet_slo_serving_p99_ms,
                             _serving_p99_ms))
    if obs_cfg.fleet_slo_serving_qps_min > 0:
        rules.append(SloRule("serving_qps", "lower",
                             obs_cfg.fleet_slo_serving_qps_min,
                             _serving_qps))
    if obs_cfg.fleet_slo_ring_occupancy_high < 1.0:
        rules.append(SloRule("ring_occupancy", "upper",
                             obs_cfg.fleet_slo_ring_occupancy_high,
                             _ring_occupancy))
    if obs_cfg.fleet_slo_ring_occupancy_low > 0.0:
        rules.append(SloRule("ring_occupancy_floor", "lower",
                             obs_cfg.fleet_slo_ring_occupancy_low,
                             _ring_occupancy))
    if obs_cfg.fleet_slo_replay_add_qps_high > 0:
        rules.append(SloRule("replay_add_qps", "upper",
                             obs_cfg.fleet_slo_replay_add_qps_high,
                             _replay_add_qps_per_shard))
    if obs_cfg.fleet_slo_endpoint_alive:
        rules.append(SloRule("endpoints_alive", "upper", 0.0,
                             _endpoints_down))
    return rules


def engine_from_config(obs_cfg, emit=None) -> SloEngine:
    return SloEngine(
        rules_from_config(obs_cfg),
        window_s=obs_cfg.fleet_slo_window_s,
        burn_threshold=obs_cfg.fleet_slo_burn_threshold,
        clear_threshold=obs_cfg.fleet_slo_clear_threshold,
        min_samples=obs_cfg.fleet_slo_min_samples,
        emit=emit,
    )


# ---------------------------------------------------------------------------
# Endpoints + the aggregator.
# ---------------------------------------------------------------------------


class _Endpoint:
    __slots__ = ("name", "kind", "url", "shard_spec", "snapshot_fn",
                 "alive", "scrape_failures", "consecutive_failures",
                 "last_ok_t", "last_error", "snapshot", "prev_qps_mark")

    def __init__(self, name: str, kind: str, url: Optional[str] = None,
                 shard_spec: Optional[dict] = None, snapshot_fn=None):
        self.name = name
        self.kind = kind               # trainer | replica | shard | host
        self.url = url                 # /varz base for HTTP endpoints
        self.shard_spec = shard_spec   # {host, port, token, id, incarnation}
        self.snapshot_fn = snapshot_fn  # in-process /varz twin (add_local)
        self.alive = False
        self.scrape_failures = 0
        self.consecutive_failures = 0
        self.last_ok_t = 0.0
        self.last_error: Optional[str] = None
        self.snapshot: Optional[dict] = None
        self.prev_qps_mark: Optional[Tuple[float, float]] = None

    def summary(self, now: float) -> dict:
        return {
            "kind": self.kind,
            "alive": self.alive,
            "scrape_failures": self.scrape_failures,
            "consecutive_failures": self.consecutive_failures,
            "last_ok_age_s": (round(now - self.last_ok_t, 3)
                              if self.last_ok_t else None),
            "last_error": self.last_error,
            "addr": self.url or (
                f"{self.shard_spec['host']}:{self.shard_spec['port']}"
                if self.shard_spec
                else ("local" if self.snapshot_fn is not None else None)
            ),
        }


def _endpoint_detail(ep: "_Endpoint") -> dict:
    """The per-row numbers obs_top --fleet renders (a curated slice of
    the endpoint's last snapshot, by kind)."""
    snap = ep.snapshot or {}
    if ep.kind == "shard":
        op = snap.get("op_ms") or {}
        return {"size": snap.get("size"), "requests": snap.get("requests"),
                "p95_ms": op.get("p95_ms"),
                "torn_frames": snap.get("torn_frames"),
                "incarnation": snap.get("incarnation")}
    if ep.kind == "replica":
        snet = snap.get("serving_net") \
            or (snap.get("serving") or {}).get("net") or {}
        lat = snet.get("latency") or {}
        return {"requests": snet.get("requests"),
                "p95_ms": lat.get("p95_ms"),
                "shed": snet.get("shed"),
                "param_version": snet.get("param_version")}
    ln = snap.get("learner") or {}
    age = (snap.get("lineage") or {}).get("age_at_sample") or {}
    return {"step": ln.get("step"),
            "steps_per_sec": ln.get("steps_per_sec"),
            "workers": len(snap.get("workers") or {}),
            "age_p95_ms": age.get("p95_ms")}


class FleetAggregator:
    """Scrape → merge → serve.  See the module docstring.

    Construction is passive; ``start()`` begins the scrape thread (or
    call ``scrape_once()`` yourself — tests and the smoke drive sweeps
    deterministically).  ``serve(port)`` mounts the rollup exporter."""

    def __init__(self, *, scrape_interval_s: float = 1.0,
                 scrape_timeout_s: float = 2.0,
                 slo: Optional[SloEngine] = None,
                 window_s: float = 30.0,
                 scrape_workers: int = 8,
                 emit=None, jsonl_stream=None):
        self._interval = float(scrape_interval_s)
        self._timeout = float(scrape_timeout_s)
        self._window_s = float(window_s)
        # Concurrent scrape plane: endpoints are fetched on a bounded
        # pool under one TOTAL-cycle deadline, so a dead member costs the
        # sweep one timeout, not N of them — the serial loop stretched
        # cadence by N×timeout and skewed every windowed SLO burn rate.
        self._workers = max(1, int(scrape_workers))
        self._pool: Optional[ThreadPoolExecutor] = None
        self._inflight: Dict[str, object] = {}   # name -> still-running Future
        self.timeline = None                      # attach_timeline()
        # Windowed twins of the cumulative merged histograms (the values
        # the SLO extractors prefer — see _BucketWindow).
        self._age_window = _BucketWindow(window_s=window_s)
        self._serving_window = _BucketWindow(window_s=window_s)
        self._emit = emit if emit is not None else (
            lambda name, **f: emit_event(name, stream=jsonl_stream, **f)
        )
        self._jsonl = jsonl_stream
        self.slo = slo if slo is not None else SloEngine([], emit=self._emit)
        if slo is not None and slo._emit is None:
            slo._emit = self._emit
        self._lock = threading.Lock()
        self._eps: "OrderedDict[str, _Endpoint]" = OrderedDict()
        self._replay_files: List[dict] = []   # {path, digest}
        self._registry_fn: Optional[Callable[[], dict]] = None
        self._member_adopted: set = set()
        self._membership: dict = {}
        self.membership_adopts = 0
        self._traces: "OrderedDict[int, dict]" = OrderedDict()
        self._rollup: dict = {"endpoints": {}}
        self.scrapes = 0
        self.scrape_failures = 0
        self.sweeps = 0
        self.last_sweep_t = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._server = None
        self.registry = None
        self.health = None

    # -- discovery ---------------------------------------------------------

    def add_varz(self, name: str, url: str, kind: str = "trainer") -> None:
        """Register one HTTP /varz endpoint (trainer, serving replica, a
        remote host's exporter).  Re-registering a name replaces its URL
        (a respawned replica announces a fresh port) and keeps its
        failure history."""
        base = url.rstrip("/")
        if not base.endswith("/varz"):
            base += "/varz"
        with self._lock:
            ep = self._eps.get(name)
            if ep is None or ep.kind != kind:
                self._eps[name] = _Endpoint(name, kind, url=base)
            else:
                ep.url = base

    def add_local(self, name: str, snapshot_fn, kind: str = "trainer") -> None:
        """Register an IN-PROCESS endpoint: ``snapshot_fn()`` returns the
        same sectioned dict its /varz would serve (e.g. a registry's
        ``snapshot``).  How a trainer-hosted aggregator (the autopilot's
        sensor) reads its own process without an HTTP round trip — the
        merge arithmetic and liveness accounting are identical."""
        with self._lock:
            self._eps[name] = _Endpoint(name, kind, snapshot_fn=snapshot_fn)

    def remove_endpoint(self, name: str) -> None:
        """Forget one endpoint (a replica retired by the autopilot leaves
        the fleet ON PURPOSE — keeping it registered would read as a
        liveness breach)."""
        with self._lock:
            self._eps.pop(name, None)

    def watch_replay_endpoints(self, path: str) -> None:
        """Discover replay shards from the fleet's endpoints file (the
        atomic tmp+rename publication clients already re-resolve); the
        file's CONTENT digest gates the re-read each sweep — mtime has
        filesystem-granularity resolution, so a rewrite within the same
        tick (respawn storms do this) would be invisible to an
        mtime-equality early-out."""
        self._replay_files.append({"path": path, "digest": None})
        self._refresh_replay_files()

    def _refresh_replay_files(self) -> None:
        for src in self._replay_files:
            try:
                with open(src["path"], "rb") as f:
                    raw = f.read()
                digest = zlib.crc32(raw)
                if digest == src["digest"]:
                    continue
                doc = json.loads(raw.decode("utf-8"))
                src["digest"] = digest
            except (OSError, ValueError):
                continue
            token = int(doc.get("token", 0))
            for s in doc.get("shards", []):
                name = f"replay_shard{int(s['id'])}"
                spec = {
                    "id": int(s["id"]), "host": s["host"],
                    "port": int(s["port"]), "token": token,
                    "incarnation": int(s.get("incarnation", -1)),
                }
                with self._lock:
                    ep = self._eps.get(name)
                    if ep is None:
                        self._eps[name] = _Endpoint(name, "shard",
                                                    shard_spec=spec)
                    else:
                        ep.shard_spec = spec

    # -- membership adoption (fleet discovery plane) -----------------------

    def bind_registry(self, registry) -> None:
        """Adopt fleet membership from an in-process
        :class:`~ape_x_dqn_tpu.fleet.registry.FleetRegistry`: every sweep
        re-reads ``registry.snapshot()`` and reconciles the endpoint set
        against it — replay shards become stats-RPC scrape specs keyed by
        their announced slot base, serving replicas and worker hosts join
        by their announced ``varz_url``.  Under ``fleet.discovery =
        "registry"`` this REPLACES the endpoints-file watch and the
        driver-handed replica ports: the membership registry is the one
        source of scrape-target truth."""
        self._registry_fn = registry.snapshot
        self.adopt_membership(registry.snapshot())

    def adopt_membership(self, snapshot: dict) -> None:
        """Reconcile the endpoint set against one membership snapshot
        (also the ``on_membership`` hook shape a FleetAnnouncer pushes).
        Members that left (reshard, retire, TTL expiry) drop their
        endpoints ON PURPOSE — a departed member must not read as a
        liveness breach."""
        snapshot = snapshot or {}
        members = snapshot.get("members") or {}
        token = int(snapshot.get("token", 0))
        version = int(snapshot.get("version", 0))
        adopted: set = set()
        draining: List[str] = []
        by_kind: Dict[str, int] = {}
        for name, doc in members.items():
            kind = str(doc.get("kind", ""))
            by_kind[kind] = by_kind.get(kind, 0) + 1
            if doc.get("draining"):
                draining.append(name)
            if kind == "replay_shard":
                cap = int(doc.get("capacity", 0))
                port = int(doc.get("port", 0))
                if cap <= 0 or port <= 0:
                    continue
                sid = int(doc.get("base", 0)) // cap
                ep_name = f"replay_shard{sid}"
                spec = {
                    "id": sid, "host": doc.get("host") or "127.0.0.1",
                    "port": port, "token": token,
                    "incarnation": int(doc.get("incarnation", -1)),
                }
                with self._lock:
                    ep = self._eps.get(ep_name)
                    if ep is None:
                        self._eps[ep_name] = _Endpoint(ep_name, "shard",
                                                       shard_spec=spec)
                    else:
                        ep.shard_spec = spec
                adopted.add(ep_name)
            elif doc.get("varz_url"):
                ep_kind = {"serving_replica": "replica",
                           "worker_host": "host"}.get(kind, "trainer")
                self.add_varz(name, str(doc["varz_url"]), kind=ep_kind)
                adopted.add(name)
        for stale in self._member_adopted - adopted:
            self.remove_endpoint(stale)
        self._member_adopted = adopted
        if version != self._membership.get("version"):
            self.membership_adopts += 1
        self._membership = {
            "version": version,
            "incarnation": int(snapshot.get("incarnation", 0)),
            "members": len(members),
            "by_kind": by_kind,
            "draining": sorted(draining),
            "adopted_endpoints": len(adopted),
            "adopts": self.membership_adopts,
        }

    # -- scraping ----------------------------------------------------------

    def _scrape_http(self, ep: _Endpoint) -> dict:
        with urllib.request.urlopen(ep.url, timeout=self._timeout) as r:
            return json.load(r)

    def _scrape_shard(self, ep: _Endpoint) -> dict:
        # Lazy, numpy-only import: the stats RPC rides the replay plane's
        # own client (hello/ack/deadline discipline for free).
        from ape_x_dqn_tpu.replay.service import ShardClient

        spec = ep.shard_spec
        client = ShardClient(
            spec["id"], spec["host"], spec["port"], token=spec["token"],
            client_id=(os.getpid() << 16) ^ 0xF1EE7, codec="off",
            connect_timeout_s=self._timeout, io_timeout_s=self._timeout,
        )
        try:
            return client.shard_stats(timeout=self._timeout)
        finally:
            client.close()

    def _fetch(self, ep: _Endpoint) -> dict:
        if ep.snapshot_fn is not None:
            return dict(ep.snapshot_fn())
        if ep.kind == "shard":
            return self._scrape_shard(ep)
        return self._scrape_http(ep)

    def _scrape_all(self, eps: List[_Endpoint]) -> List[tuple]:
        """Fetch every endpoint concurrently (bounded pool) under one
        total-cycle deadline.  Returns ``(ep, snapshot_or_None,
        error_or_None)`` in endpoint order.  An endpoint whose PREVIOUS
        fetch is still wedged (a hang the socket timeout can't see —
        e.g. a snapshot_fn stuck on a lock) is skipped and counted as a
        failure instead of stacking another worker behind it; the
        straggler's eventual result is discarded."""
        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self._workers, thread_name_prefix="fleet-scrape"
            )
        # One endpoint timeout of budget for everyone at once, plus
        # pool-queueing slack when the fleet outnumbers the workers.
        waves = (len(eps) + self._workers - 1) // max(1, self._workers)
        deadline = time.monotonic() + self._timeout * max(1, waves) + 0.25
        futs: Dict[str, object] = {}
        results: List[tuple] = []
        for ep in eps:
            old = self._inflight.get(ep.name)
            if old is not None and not old.done():
                results.append((ep, None,
                                "ScrapeStuck: previous scrape still in flight"))
                continue
            self._inflight.pop(ep.name, None)
            futs[ep.name] = self._pool.submit(self._fetch, ep)
        for ep in eps:
            fut = futs.get(ep.name)
            if fut is None:
                continue
            try:
                snap = fut.result(
                    timeout=max(0.0, deadline - time.monotonic())
                )
                results.append((ep, snap, None))
            except _FutureTimeout:
                self._inflight[ep.name] = fut
                results.append((ep, None,
                                "ScrapeDeadline: cycle deadline exceeded"))
            except Exception as e:  # noqa: BLE001 — ANY scrape fault = endpoint down, never a sweep crash
                results.append((ep, None, f"{type(e).__name__}: {e}"))
        order = {ep.name: i for i, ep in enumerate(eps)}
        results.sort(key=lambda r: order.get(r[0].name, len(order)))
        return results

    def scrape_once(self, now: Optional[float] = None) -> dict:
        """One full sweep: scrape every endpoint (concurrently, one
        total-cycle deadline), rebuild the rollup, evaluate the SLO
        rules, append the sweep to the timeline when one is attached.
        Returns the rollup (also kept for the /varz provider).  A
        failing endpoint is marked down and the sweep continues — the
        fleet view never dies of a member's death."""
        if self._registry_fn is not None:
            try:
                self.adopt_membership(self._registry_fn())
            except Exception:  # noqa: BLE001 — membership adoption must never kill the sweep
                pass
        self._refresh_replay_files()
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            eps = list(self._eps.values())
        for ep, snap, err in self._scrape_all(eps):
            self.scrapes += 1
            if err is not None:
                self.scrape_failures += 1
                ep.scrape_failures += 1
                ep.consecutive_failures += 1
                ep.alive = False
                ep.last_error = err
                continue
            ep.alive = True
            ep.consecutive_failures = 0
            ep.last_ok_t = now
            ep.last_error = None
            ep.snapshot = snap
        rollup = self._merge(eps, now)
        with self._lock:
            self._rollup = rollup
        slo_status = self.slo.evaluate(rollup, now=now)
        if self.timeline is not None:
            try:
                self.timeline.append_sweep(rollup, slo_status, now=now)
                self._lift_timeline_windows(rollup, now)
            except Exception:  # noqa: BLE001 — the recorder must never kill the sweep
                pass
        self.sweeps += 1
        self.last_sweep_t = time.monotonic()
        if self._jsonl is not None:
            try:
                compact = {k: rollup.get(k) for k in (
                    "alive", "expected", "age_of_experience", "inference",
                    "serving", "replay", "membership",
                    "ring_occupancy_max", "scrape_failures",
                )}
                rec = stamp_record({"fleet": compact,
                                    "slo": self.slo.status()})
                self._jsonl.write(json.dumps(rec) + "\n")
                self._jsonl.flush()
            except (OSError, ValueError):
                pass
        return rollup

    # -- timeline (flight-data recorder) -----------------------------------

    def attach_timeline(self, store, rebuild: bool = True) -> None:
        """Attach a :class:`~ape_x_dqn_tpu.obs.timeline.TimelineStore`
        (duck-typed — fleet.py stays import-light): every sweep appends
        one compacted delta record, and — the respawn story — the SLO
        engine's burn/clear windows and rule states are REBUILT from the
        timeline tail right now, so a restarted aggregator resumes the
        previous incarnation's alarm state instead of opening a blind
        window that false-clears a live breach."""
        self.timeline = store
        if rebuild:
            try:
                store.rebuild_slo(self.slo)
            except Exception:  # noqa: BLE001 — a corrupt tail degrades to a cold start, never a crash
                pass

    def _lift_timeline_windows(self, rollup: dict, now: float) -> None:
        """Windowed rates from the recorder onto the rollup: the
        scrape-to-scrape ``qps`` / ``add_qps`` are instantaneous (one
        quiet sweep reads as idleness); these are the smoothed trailing-
        window twins the autopilot's idle rules prefer."""
        win = self._window_s
        qps = self.timeline.rate("serving_replies", win, now=now)
        if qps is not None:
            (rollup.get("serving") or {}).setdefault("window", {})[
                "qps"] = round(qps, 2)
        add = self.timeline.rate("replay_added", win, now=now)
        rep = rollup.get("replay")
        if add is not None and isinstance(rep, dict):
            rep["window"] = {"add_qps": round(add, 2), "window_s": win}

    # -- merge arithmetic --------------------------------------------------

    def _collect_spans(self, snap: dict) -> List[dict]:
        out: List[dict] = []
        for holder in (
            snap.get("trace_spans"),                       # trainer + shard
            (snap.get("serving_net") or {}).get("recent_spans"),
            ((snap.get("serving") or {}).get("net") or {}).get(
                "recent_spans"),
        ):
            if isinstance(holder, dict):
                out.extend(holder.get("spans") or [])
            elif isinstance(holder, list):
                out.extend(holder)
        return [s for s in out if isinstance(s, dict) and s.get("trace_id")]

    def _fold_traces(self, spans: List[dict]) -> None:
        for span in spans:
            tid = int(span["trace_id"])
            rec = self._traces.get(tid)
            if rec is None:
                rec = self._traces[tid] = {"trace_id": tid, "spans": {},
                                           "t_new": 0.0}
                while len(self._traces) > _MAX_TRACES:
                    self._traces.popitem(last=False)
            key = (span.get("pid"), span.get("hop"), span.get("t0_s"))
            rec["spans"][key] = span
            rec["t_new"] = max(rec["t_new"], float(span.get("t1_s") or 0.0))

    def _timelines(self) -> List[dict]:
        """The newest assembled multi-process timelines: spans sorted by
        start time, the distinct-pid set, and whether an RPC hop's two
        halves are both present (a client-side and a server-side span of
        the same trace from different pids)."""
        out = []
        for rec in self._traces.values():
            spans = sorted(rec["spans"].values(),
                           key=lambda s: s.get("t0_s") or 0.0)
            pids = sorted({s.get("pid") for s in spans
                           if s.get("pid") is not None})
            if len(pids) < 2:
                continue
            out.append({
                "trace_id": rec["trace_id"],
                "pids": pids,
                "hops": [s.get("hop") for s in spans],
                "spans": spans,
                "t_new": rec["t_new"],
            })
        out.sort(key=lambda t: t["t_new"], reverse=True)
        for t in out:
            t.pop("t_new", None)
        return out[:_ROLLUP_TRACES]

    def _merge(self, eps: List[_Endpoint], now: float) -> dict:
        age_buckets: dict = {}
        age_count = 0
        serving_buckets: dict = {}
        serving_count = 0
        serving_qps = 0.0
        serving_replicas = 0
        shard_ms_buckets: dict = {}
        shard_counters: dict = {}
        shards_alive = 0
        replay_add_qps = 0.0
        # Per-param_version serving telemetry (ROADMAP item 3's canary
        # sensor) + the newest bucket exemplars, merged across replicas.
        version_counts: Dict[str, int] = {}
        version_buckets: Dict[str, dict] = {}
        serving_exemplars: dict = {}
        op_exemplars: dict = {}
        rtt_exemplars: dict = {}
        inference_p99: List[float] = []
        inference_stall = 0.0
        inference_replies = 0
        ring_occ: List[float] = []
        spans: List[dict] = []
        autopilot: Optional[dict] = None
        for ep in eps:
            snap = ep.snapshot
            if snap is None:
                continue
            spans.extend(self._collect_spans(snap))
            if ep.kind == "shard":
                if ep.alive:
                    shards_alive += 1
                    op = snap.get("op_ms") or {}
                    shard_ms_buckets = merge_bucket_dicts(
                        shard_ms_buckets, op.get("buckets") or {}
                    )
                    if isinstance(op.get("exemplars"), dict):
                        op_exemplars.update(op["exemplars"])
                    shard_counters = merge_counter_maps(
                        shard_counters,
                        {k: snap[k] for k in _SHARD_SUM_KEYS if k in snap},
                    )
                    # Per-shard ingest rate from prev-mark deltas of the
                    # monotone total_added counter (the serving qps
                    # pattern) — THE autopilot grow signal: occupancy
                    # saturates once a ring wraps, add rate does not.
                    added = float(snap.get("total_added", 0))
                    mark = ep.prev_qps_mark
                    if mark is not None and now > mark[0]:
                        replay_add_qps += max(0.0, added - mark[1]) \
                            / (now - mark[0])
                    ep.prev_qps_mark = (now, added)
                continue
            # HTTP/local endpoints: lineage / inference / serving /
            # workers / autopilot.
            if isinstance(snap.get("autopilot"), dict):
                # The controller's own state rides its trainer's /varz;
                # lift the newest live one onto the rollup so obs_top
                # --fleet renders it next to the SLO states.
                autopilot = snap["autopilot"]
            lineage = snap.get("lineage") or {}
            age = lineage.get("age_at_sample") or {}
            if age.get("count"):
                age_buckets = merge_bucket_dicts(
                    age_buckets, age.get("buckets_s") or {}
                )
                age_count += int(age.get("count", 0))
            inf = snap.get("inference") or {}
            rtt = inf.get("rtt") or {}
            if rtt.get("count"):
                inference_p99.append(float(rtt.get("p99_ms", 0.0)))
                inference_stall += float(inf.get("stall_ms", 0.0))
                inference_replies += int(inf.get("replies", 0))
            if isinstance(inf.get("rtt_exemplars"), dict):
                rtt_exemplars.update(inf["rtt_exemplars"])
            snet = snap.get("serving_net") \
                or (snap.get("serving") or {}).get("net")
            if isinstance(snet, dict) and ep.kind == "replica":
                if ep.alive:
                    serving_replicas += 1
                serving_buckets = merge_bucket_dicts(
                    serving_buckets, snet.get("latency_buckets") or {}
                )
                lat = snet.get("latency") or {}
                serving_count += int(lat.get("count", 0))
                if isinstance(snet.get("latency_exemplars"), dict):
                    serving_exemplars.update(snet["latency_exemplars"])
                for ver, row in (snet.get("by_version") or {}).items():
                    if not isinstance(row, dict):
                        continue
                    ver = str(ver)
                    version_counts[ver] = version_counts.get(ver, 0) \
                        + int(row.get("replies", 0))
                    version_buckets[ver] = merge_bucket_dicts(
                        version_buckets.get(ver, {}),
                        row.get("latency_buckets") or {},
                    )
                replies = float(snet.get("replies", 0))
                mark = ep.prev_qps_mark
                if mark is not None and now > mark[0]:
                    serving_qps += max(0.0, replies - mark[1]) \
                        / (now - mark[0])
                ep.prev_qps_mark = (now, replies)
            xp = snap.get("xp_transport") or {}
            ring_bytes = float(xp.get("ring_bytes") or 0)
            if ring_bytes > 0:
                for w in (snap.get("workers") or {}).values():
                    if isinstance(w, dict):
                        ring_occ.append(
                            float(w.get("ring_backlog_bytes", 0))
                            / ring_bytes
                        )
        self._fold_traces(spans)
        self._age_window.feed(age_buckets, now)
        self._serving_window.feed(serving_buckets, now)
        age_win_n = self._age_window.count()
        srv_win_n = self._serving_window.count()
        rollup: dict = {
            "endpoints": {
                ep.name: {**ep.summary(now), "detail": _endpoint_detail(ep)}
                for ep in eps
            },
            "expected": len(eps),
            "alive": sum(1 for ep in eps if ep.alive),
            "scrapes": self.scrapes,
            "scrape_failures": self.scrape_failures,
            "sweeps": self.sweeps,
            "age_of_experience": {
                "count": age_count,
                "p50_s": round(bucket_percentile(age_buckets, 50), 4)
                if age_count else None,
                "p95_s": round(bucket_percentile(age_buckets, 95), 4)
                if age_count else None,
                "p99_s": round(bucket_percentile(age_buckets, 99), 4)
                if age_count else None,
                "buckets_s": age_buckets,
                # Trailing-window distribution (see _BucketWindow): the
                # value the age SLO rule actually evaluates.
                "window": {
                    "count": age_win_n,
                    "p50_s": round(self._age_window.percentile(50), 4)
                    if age_win_n else None,
                    "p95_s": round(self._age_window.percentile(95), 4)
                    if age_win_n else None,
                },
            },
            "inference": {
                "rtt_p99_ms_max": (round(max(inference_p99), 3)
                                   if inference_p99 else None),
                "stall_ms": round(inference_stall, 1),
                "replies": inference_replies,
                "trainers_reporting": len(inference_p99),
                "rtt_exemplars": rtt_exemplars,
            },
            "serving": {
                "replicas": serving_replicas,
                "count": serving_count,
                "p50_ms": round(
                    bucket_percentile(serving_buckets, 50) * 1e3, 3)
                if serving_count else None,
                "p95_ms": round(
                    bucket_percentile(serving_buckets, 95) * 1e3, 3)
                if serving_count else None,
                "p99_ms": round(
                    bucket_percentile(serving_buckets, 99) * 1e3, 3)
                if serving_count else None,
                "qps": round(serving_qps, 2),
                "latency_buckets": serving_buckets,
                # Canary sensor: the same latency split by the
                # param_version each reply carried, fleet-merged.
                "by_version": {
                    ver: {
                        "replies": version_counts.get(ver, 0),
                        "p50_ms": round(
                            bucket_percentile(bkts, 50) * 1e3, 3)
                        if any(bkts.values()) else None,
                        "p99_ms": round(
                            bucket_percentile(bkts, 99) * 1e3, 3)
                        if any(bkts.values()) else None,
                    }
                    for ver, bkts in sorted(version_buckets.items())
                },
                "exemplars": serving_exemplars,
                "window": {
                    "count": srv_win_n,
                    "p50_ms": round(
                        self._serving_window.percentile(50) * 1e3, 3)
                    if srv_win_n else None,
                    "p99_ms": round(
                        self._serving_window.percentile(99) * 1e3, 3)
                    if srv_win_n else None,
                },
            },
            "replay": {
                "shards_alive": shards_alive,
                "add_qps": round(replay_add_qps, 2),
                "occupancy": (
                    round(float(shard_counters.get("size", 0))
                          / float(shard_counters["capacity"]), 4)
                    if shard_counters.get("capacity") else None),
                "op_p95_ms": round(
                    bucket_percentile(shard_ms_buckets, 95) * 1e3, 3)
                if shard_ms_buckets else None,
                "op_buckets": shard_ms_buckets,
                "op_exemplars": op_exemplars,
                **shard_counters,
            },
            "membership": dict(self._membership) if self._membership
            else None,
            "ring_occupancy_max": (round(max(ring_occ), 4)
                                   if ring_occ else None),
            "autopilot": autopilot,
            "traces": self._timelines(),
        }
        return rollup

    # -- serving the rollup ------------------------------------------------

    def rollup(self) -> dict:
        """The ``fleet`` /varz section: the newest completed sweep."""
        with self._lock:
            return self._rollup

    def slo_status(self) -> dict:
        return self.slo.status()

    def serve(self, port: int = 0, host: str = "127.0.0.1"):
        """Mount the rollup exporter: ``/varz`` carries the ``fleet`` +
        ``slo`` sections, ``/metrics`` flattens them, ``/healthz``
        reflects ONLY the aggregator's own scrape loop — dead fleet
        endpoints ride the body, they never 503 the rollup."""
        from ape_x_dqn_tpu.obs.exporter import ObsServer
        from ape_x_dqn_tpu.obs.registry import Health, MetricsRegistry

        self.registry = MetricsRegistry()
        self.registry.gauge(
            "fleet/scrapes", help="endpoint scrapes attempted",
        ).set_fn(lambda: self.scrapes)
        self.registry.gauge(
            "fleet/scrape_failures", help="endpoint scrapes that failed",
        ).set_fn(lambda: self.scrape_failures)
        self.registry.gauge(
            "fleet/slo_breaches", help="slo ok->breach transitions",
        ).set_fn(lambda: self.slo.breaches)
        self.registry.gauge(
            "fleet/slo_clears", help="slo breach->ok transitions",
        ).set_fn(lambda: self.slo.clears)
        self.registry.register_provider("fleet", self.rollup)
        self.registry.register_provider("slo", self.slo_status)
        if self.timeline is not None:
            self.registry.register_provider("timeline", self.timeline.stats)
        self.health = Health(stale_after_s=max(10.0, 5 * self._interval))
        self.health.register(
            "scrape_loop", lambda: time.monotonic() - self.last_sweep_t
        )
        self._server = ObsServer(self.registry, self.health,
                                 port=port, host=host)
        return self._server

    @property
    def port(self) -> Optional[int]:
        return self._server.port if self._server is not None else None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "FleetAggregator":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="fleet-aggregator", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — the sweep must survive anything a member sends
                self.scrape_failures += 1

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._server is not None:
            self._server.close()
            self._server = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
        if self.timeline is not None:
            # Clean shutdown commits the active segment; a SIGKILL skips
            # this and the next incarnation adopts the tail instead.
            try:
                self.timeline.close()
            except Exception:  # noqa: BLE001 — shutdown is best-effort, the tail is adoptable
                pass
