"""Flight recorder: a bounded ring of recent events, flushed to a
post-mortem file when the process dies telling — and salvageable from
shared memory when it dies without a word.

``_salvage_incarnation`` forensics were guesswork: after a SIGKILL the
parent knew only what the experience ring implied (records committed, a
torn tail).  The recorder turns that into data, three ways:

  * **In memory** — ``record(kind, ...)`` appends to a deque of
    ``obs.recorder_depth`` recent events: cheap enough for per-quantum /
    per-emit cadence, never per step.
  * **Mirrored to shm** — with a ``shm_sink`` (the worker's
    ``WorkerStatsBlock``), every event also lands in the block's event
    ring, so the parent can read a SIGKILLed worker's last moves.
  * **Dumped on fault/SIGTERM** — ``dump()`` writes one JSON file under
    ``<postmortem_dir>/`` (tmp + rename: a crash mid-dump leaves no torn
    artifact); ``install_sigterm`` chains the previous handler so a
    terminated trainer flushes before dying.

Import-light by contract (stdlib only): worker children construct one
before jax exists in their process.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional


class FlightRecorder:
    def __init__(self, name: str = "proc", depth: int = 256,
                 shm_sink=None):
        self.name = name
        self._events: deque = deque(maxlen=int(depth))
        self._sink = shm_sink
        self._lock = threading.Lock()
        self._snapshot_fns: Dict[str, Callable[[], dict]] = {}
        self.dumped: List[str] = []

    def add_snapshot_provider(self, name: str,
                              fn: Callable[[], dict]) -> None:
        """State captured AT DUMP TIME (registry snapshot, pool stats) —
        the "what was true when it died" half of a post-mortem."""
        self._snapshot_fns[name] = fn

    def record(self, kind: str, **fields) -> dict:
        rec = {"t": round(time.monotonic(), 4), "kind": kind, **fields}
        with self._lock:
            self._events.append(rec)
        if self._sink is not None:
            try:
                self._sink.record_event(rec)
            except Exception:  # noqa: BLE001 — recording must never kill
                pass
        return rec

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def dump(self, out_dir: str, reason: str,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write one post-mortem JSON under ``out_dir``; returns the path
        (None if ``out_dir`` is falsy — recording configured off).  Never
        raises: the dump runs on failure paths where a second exception
        would mask the first."""
        if not out_dir:
            return None
        try:
            os.makedirs(out_dir, exist_ok=True)
            snapshots: dict = {}
            for name, fn in self._snapshot_fns.items():
                try:
                    snapshots[name] = fn()
                except Exception as e:  # noqa: BLE001
                    snapshots[name] = {
                        "error": f"{type(e).__name__}: {e}"
                    }
            record = {
                "name": self.name,
                "reason": reason,
                "pid": os.getpid(),
                "wall_time": time.time(),
                "t_mono": time.monotonic(),
                "events": self.events(),
                "snapshots": snapshots,
                "extra": extra or {},
            }
            fname = (f"{self.name}-pid{os.getpid()}-{reason}-"
                     f"{int(time.time() * 1e3)}.json")
            path = os.path.join(out_dir, fname)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(record, f, indent=1, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self.dumped.append(path)
            return path
        except Exception:  # noqa: BLE001 — see docstring
            return None

    def install_sigterm(self, out_dir: str) -> bool:
        """Flush-on-SIGTERM: dump, then run the previously-installed
        handler (or re-raise the default kill).  Signal handlers can only
        live on the main thread — returns False (no-op) elsewhere, which
        is the serve/--attach and test-thread case."""
        if threading.current_thread() is not threading.main_thread():
            return False
        prev = signal.getsignal(signal.SIGTERM)

        def _handler(signum, frame):
            self.dump(out_dir, "sigterm")
            if callable(prev):
                prev(signum, frame)
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _handler)
        return True


def write_postmortem(out_dir: str, name: str, reason: str,
                     record: dict) -> Optional[str]:
    """One-shot post-mortem writer for records assembled by someone else —
    the parent writing a SIGKILLed worker's salvaged stats block
    (runtime/process_actors._salvage_incarnation).  Same tmp+rename
    discipline, same never-raises contract."""
    if not out_dir:
        return None
    try:
        os.makedirs(out_dir, exist_ok=True)
        fname = f"{name}-{reason}-{int(time.time() * 1e3)}.json"
        path = os.path.join(out_dir, fname)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"name": name, "reason": reason,
                       "wall_time": time.time(), **record}, f,
                      indent=1, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 — salvage must not kill the parent
        return None
