"""Flight-data recorder: the per-run on-disk fleet timeline.

Every sensor the fleet plane grew (PRs 14-16) is point-in-time: the
rollup serves only the latest sweep, the SLO engine's burn windows live
in aggregator memory, and "what happened at minute 43" — the question
Horgan et al. 2018 tune Ape-X by and SEED RL's bytes-over-time
accounting requires — has no durable answer.  This module is that
answer: a bounded snapshot ring on disk that the
:class:`~ape_x_dqn_tpu.obs.fleet.FleetAggregator` appends one compacted
record to per scrape sweep, plus the windowed query API that
re-aggregates any time span bit-consistently with the live rollup.

Disk format — the repo's existing chunk discipline, record-framed:

  * **Records** — each sweep is one CRC-framed record::

        4s TIMELINE_MAGIC "APXL" | u32 version | u32 flags
        | u64 payload_len | u32 crc32(payload)      + payload

    the ``utils/checkpoint_inc`` header layout over a JSON payload
    (flags bit 0: zlib).  The magic is registered in ``runtime/net.py``
    so apexlint's wire-registry checker owns it.  A truncated or
    corrupted tail (SIGKILL mid-append) fails its CRC and is dropped at
    the frame boundary, never half-parsed — the torn-tail contract.
  * **Segments + generation pruning** — records append to the active
    ``tl_<G>.seg``; at ``segment_bytes`` the segment is fsynced and
    COMMITTED into ``MANIFEST.json`` (tmp + fsync + ``os.replace`` —
    the manifest-last atomic commit the checkpoint chain uses), and a
    fresh generation opens.  When committed bytes exceed ``max_bytes``
    the oldest generations are pruned — the store is a ring, bounded by
    construction.  A reopened store (aggregator respawn) adopts the
    previous incarnation's uncommitted tail (CRC-verified), commits it,
    and starts its own generation.

Delta compaction — why disk windows match the live rollup bit-for-bit:
cumulative histograms are stored as per-sweep BUCKET-WISE deltas
(clamped at zero, exactly ``_BucketWindow.feed``'s respawn-tolerant
arithmetic) and cumulative counters as per-sweep deltas; a windowed
query re-sums the deltas with ``merge_bucket_dicts`` and re-derives
percentiles with ``bucket_percentile`` — the same two functions the
live window uses, over the same per-sweep deltas, so
``percentile("serving_s", 99, now - w, now)`` equals the in-memory
rollup's ``serving.window.p99_ms`` by construction, not by tolerance.

The tail also rebuilds the SLO engine after a respawn
(:meth:`TimelineStore.rebuild_slo`): each record carries every rule's
(value, violated, state) sample, so a restarted aggregator refills the
burn/clear windows and re-adopts each rule's state instead of opening a
blind window that false-clears a live breach.

Import-light by contract (stdlib at module scope, like obs/fleet.py):
``obs_top --timeline`` and ``tools/obs_diff.py`` must read a run's
timeline on any host in milliseconds.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

from ape_x_dqn_tpu.runtime.net import TIMELINE_MAGIC
from ape_x_dqn_tpu.utils.metrics import bucket_percentile, merge_bucket_dicts

# Record framing: the utils/checkpoint_inc header layout (magic |
# version | flags | payload_len | crc32) over a JSON payload.
_REC_HDR = struct.Struct("<4sIIQI")
_REC_VERSION = 1
_FLAG_ZLIB = 1
_COMPRESS_MIN = 512        # don't zlib tiny payloads
_MANIFEST = "MANIFEST.json"

#: rollup cumulative-histogram sources → timeline hist keys (seconds
#: edges, the merge_bucket_dicts vocabulary).
_HIST_KEYS = ("age_s", "serving_s", "replay_op_s")
#: rollup cumulative-counter sources → timeline counter keys.
_COUNTER_KEYS = ("serving_replies", "replay_added", "scrapes",
                 "scrape_failures")


class TimelineCorrupt(ValueError):
    """A timeline segment failed framing/CRC/decode verification."""


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _frame(record: dict, compress: bool) -> bytes:
    payload = json.dumps(record, separators=(",", ":")).encode("utf-8")
    flags = 0
    if compress and len(payload) >= _COMPRESS_MIN:
        payload = zlib.compress(payload, 1)
        flags |= _FLAG_ZLIB
    hdr = _REC_HDR.pack(TIMELINE_MAGIC, _REC_VERSION, flags, len(payload),
                        zlib.crc32(payload) & 0xFFFFFFFF)
    return hdr + payload


def read_segment(path: str) -> Tuple[List[dict], int]:
    """Decode one segment file: (records, torn).  ``torn`` is 1 when the
    file ends in bytes that fail framing or CRC — a SIGKILL mid-append
    leaves exactly one torn tail; like the net planes, a byte stream
    cannot resync past a corrupt header, so decoding stops there and the
    damage is bounded at the frame boundary."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return [], 0
    out: List[dict] = []
    off = 0
    n = len(data)
    while off < n:
        if off + _REC_HDR.size > n:
            return out, 1
        magic, version, flags, plen, crc = _REC_HDR.unpack_from(data, off)
        if magic != TIMELINE_MAGIC or version != _REC_VERSION \
                or off + _REC_HDR.size + plen > n:
            return out, 1
        payload = data[off + _REC_HDR.size: off + _REC_HDR.size + plen]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            return out, 1
        try:
            if flags & _FLAG_ZLIB:
                payload = zlib.decompress(payload)
            rec = json.loads(payload.decode("utf-8"))
        except (ValueError, zlib.error):
            return out, 1
        if isinstance(rec, dict):
            out.append(rec)
        off += _REC_HDR.size + plen
    return out, 0


def read_timeline(dir_path: str) -> dict:
    """Read-only load of a run's whole timeline (the ``obs_top
    --timeline`` / ``obs_diff`` entry point): records in append order
    across every generation — committed segments in manifest order,
    then any uncommitted tail segments — plus torn/segment counts."""
    records: List[dict] = []
    torn = 0
    seen: set = set()
    manifest_segments: List[dict] = []
    try:
        with open(os.path.join(dir_path, _MANIFEST), encoding="utf-8") as f:
            manifest_segments = list(json.load(f).get("segments") or [])
    except (OSError, ValueError):
        pass
    paths: List[str] = []
    for seg in manifest_segments:
        name = seg.get("file")
        if name:
            paths.append(os.path.join(dir_path, name))
            seen.add(name)
    try:
        extra = sorted(
            name for name in os.listdir(dir_path)
            if name.startswith("tl_") and name.endswith(".seg")
            and name not in seen
        )
    except OSError:
        extra = []
    paths.extend(os.path.join(dir_path, name) for name in extra)
    for path in paths:
        recs, t = read_segment(path)
        records.extend(recs)
        torn += t
    records.sort(key=lambda r: r.get("t", 0.0))
    return {"records": records, "torn": torn, "segments": len(paths)}


def _delta_map(prev: dict, cur: dict) -> dict:
    """Per-key ``max(0, cur - prev)`` — the _BucketWindow clamp: an
    endpoint respawn that reset its cumulative counters loses at most
    its own window contribution, never corrupts the sum."""
    return {
        k: max(0, int(v) - int(prev.get(k, 0)))
        for k, v in (cur or {}).items()
    }


class TimelineStore:
    """Bounded on-disk snapshot ring + windowed queries.  See the module
    docstring for the format; construction opens (or adopts) the store
    under ``dir_path`` and starts a fresh generation."""

    def __init__(self, dir_path: str, *, max_bytes: int = 16 << 20,
                 segment_bytes: int = 1 << 20, tail_keep_s: float = 600.0,
                 compress: bool = True):
        if segment_bytes <= 0 or max_bytes < segment_bytes:
            raise ValueError(
                "timeline needs 0 < segment_bytes <= max_bytes"
            )
        self.dir = str(dir_path)
        self._max_bytes = int(max_bytes)
        self._segment_bytes = int(segment_bytes)
        self._tail_keep_s = float(tail_keep_s)
        self._compress = bool(compress)
        self._lock = threading.Lock()
        self._segments: List[dict] = []   # committed: {gen,file,records,t0,t1,bytes}
        self._f = None
        self._gen = 0
        self._active_bytes = 0
        self._active_records = 0
        self._active_t0: Optional[float] = None
        self._active_t1: Optional[float] = None
        # In-memory tail: (t, record) within tail_keep_s of the newest —
        # where windowed queries and the SLO rebuild read from without
        # touching disk on the sweep path.
        self._tail: deque = deque()
        self._t_first: Optional[float] = None
        # Delta-compaction state (cumulative marks from the last sweep).
        self._prev_hist: Dict[str, dict] = {}
        self._prev_counters: Dict[str, int] = {}
        # Counters (the `timeline` /varz section).
        self.appends = 0
        self.rotations = 0
        self.prunes = 0
        self.torn_records = 0
        self.adopted_records = 0
        self.rebuilds = 0
        self._open()

    # -- open / adopt ------------------------------------------------------

    def _open(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        try:
            with open(os.path.join(self.dir, _MANIFEST),
                      encoding="utf-8") as f:
                self._segments = list(json.load(f).get("segments") or [])
        except (OSError, ValueError):
            self._segments = []
        committed = {s.get("file") for s in self._segments}
        max_gen = max([int(s.get("gen", 0)) for s in self._segments] or [0])
        # Adopt a dead incarnation's uncommitted tail segments: verify
        # (CRC, torn tail dropped) and commit them, so a respawn loses at
        # most the single torn record, never the window.
        try:
            orphans = sorted(
                name for name in os.listdir(self.dir)
                if name.startswith("tl_") and name.endswith(".seg")
                and name not in committed
            )
        except OSError:
            orphans = []
        for name in orphans:
            path = os.path.join(self.dir, name)
            recs, torn = read_segment(path)
            self.torn_records += torn
            try:
                gen = int(name[3:-4])
            except ValueError:
                gen = max_gen + 1
            max_gen = max(max_gen, gen)
            if not recs:
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            self.adopted_records += len(recs)
            self._segments.append({
                "gen": gen, "file": name, "records": len(recs),
                "t0": recs[0].get("t"), "t1": recs[-1].get("t"),
                "bytes": os.path.getsize(path),
            })
        self._segments.sort(key=lambda s: int(s.get("gen", 0)))
        if orphans:
            self._commit_manifest()
        # Seed the in-memory tail from the committed history so queries
        # and the SLO rebuild see the pre-respawn window immediately.
        records: List[dict] = []
        for seg in self._segments:
            recs, torn = read_segment(os.path.join(self.dir, seg["file"]))
            self.torn_records += torn
            records.extend(recs)
        records.sort(key=lambda r: r.get("t", 0.0))
        if records:
            self._t_first = float(records[0].get("t", 0.0))
            newest = float(records[-1].get("t", 0.0))
            for rec in records:
                t = float(rec.get("t", 0.0))
                if t >= newest - self._tail_keep_s:
                    self._tail.append((t, rec))
            # Resume delta marks from the newest record's cumulative
            # echo so the first post-respawn delta is vs the last
            # PERSISTED sweep, not vs zero (which would double-count the
            # whole run into one delta).
            cum = records[-1].get("cum") or {}
            self._prev_hist = {k: dict(v) for k, v in
                               (cum.get("hist") or {}).items()}
            self._prev_counters = dict(cum.get("counters") or {})
        self._gen = max_gen + 1
        self._f = open(self._active_path(), "ab")
        self._prune_locked()

    def _active_path(self) -> str:
        return os.path.join(self.dir, f"tl_{self._gen:08d}.seg")

    # -- append ------------------------------------------------------------

    def append_sweep(self, rollup: dict, slo_status: Optional[dict] = None,
                     now: Optional[float] = None) -> dict:
        """Compact one rollup sweep into a delta record and append it.
        Returns the record (tests assert on it).  Never raises on the
        sweep path — an IO fault marks the store degraded in ``stats``."""
        now = time.monotonic() if now is None else float(now)
        age = rollup.get("age_of_experience") or {}
        srv = rollup.get("serving") or {}
        rep = rollup.get("replay") or {}
        cum_hist = {
            "age_s": age.get("buckets_s") or {},
            "serving_s": srv.get("latency_buckets") or {},
            "replay_op_s": rep.get("op_buckets") or {},
        }
        cum_counters = {
            "serving_replies": int(srv.get("count") or 0),
            "replay_added": int(rep.get("total_added") or 0),
            "scrapes": int(rollup.get("scrapes") or 0),
            "scrape_failures": int(rollup.get("scrape_failures") or 0),
        }
        rec: dict = {
            "v": 1,
            "t": round(now, 6),
            "wall": round(time.time(), 3),
            "gauges": {
                "alive": rollup.get("alive"),
                "expected": rollup.get("expected"),
                "serving_replicas": srv.get("replicas"),
                "serving_qps": srv.get("qps"),
                "serving_p99_ms": (srv.get("window") or {}).get("p99_ms")
                if (srv.get("window") or {}).get("count")
                else srv.get("p99_ms"),
                "age_p95_s": (age.get("window") or {}).get("p95_s")
                if (age.get("window") or {}).get("count")
                else age.get("p95_s"),
                "shards_alive": rep.get("shards_alive"),
                "replay_add_qps": rep.get("add_qps"),
                "replay_occupancy": rep.get("occupancy"),
                "ring_occupancy_max": rollup.get("ring_occupancy_max"),
            },
            "hist": {
                key: _delta_map(self._prev_hist.get(key, {}), cum)
                for key, cum in cum_hist.items()
            },
            "counters": _delta_map(self._prev_counters, cum_counters),
            # Cumulative echo: how a reopened store resumes delta marks
            # against the last persisted sweep instead of zero.
            "cum": {"hist": cum_hist, "counters": cum_counters},
        }
        exemplars = {}
        for src_key, out_key in (("exemplars", "serving"),
                                 ("op_exemplars", "replay_op"),
                                 ("rtt_exemplars", "inference_rtt")):
            holder = srv if out_key == "serving" else (
                rep if out_key == "replay_op"
                else rollup.get("inference") or {})
            ex = holder.get(src_key)
            if ex:
                exemplars[out_key] = dict(ex)
        if exemplars:
            rec["exemplars"] = exemplars
        if slo_status:
            slo_rec: dict = {}
            for name, r in (slo_status.get("rules") or {}).items():
                value = r.get("value")
                violated = None
                if value is not None:
                    violated = (value > r.get("bound", 0.0)
                                if r.get("kind") == "upper"
                                else value < r.get("bound", 0.0))
                slo_rec[name] = {"v": value,
                                 "x": int(bool(violated))
                                 if violated is not None else None,
                                 "s": r.get("state", "ok")}
            rec["slo"] = slo_rec
        self._prev_hist = {k: dict(v) for k, v in cum_hist.items()}
        self._prev_counters = dict(cum_counters)
        self._append(rec, now)
        return rec

    def _append(self, rec: dict, now: float) -> None:
        frame = _frame(rec, self._compress)
        with self._lock:
            try:
                self._f.write(frame)
                self._f.flush()
            except (OSError, ValueError):
                return
            self.appends += 1
            self._active_bytes += len(frame)
            self._active_records += 1
            self._active_t1 = now
            if self._active_t0 is None:
                self._active_t0 = now
            if self._t_first is None:
                self._t_first = now
            self._tail.append((now, rec))
            cutoff = now - self._tail_keep_s
            while self._tail and self._tail[0][0] < cutoff:
                self._tail.popleft()
            if self._active_bytes >= self._segment_bytes:
                self._rotate_locked()

    def _rotate_locked(self) -> None:
        """Commit the active segment (fsync, then manifest tmp+rename —
        the manifest-last ordering) and open the next generation."""
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
        except (OSError, ValueError):
            pass
        self._segments.append({
            "gen": self._gen,
            "file": os.path.basename(self._active_path()),
            "records": self._active_records,
            "t0": self._active_t0, "t1": self._active_t1,
            "bytes": self._active_bytes,
        })
        self.rotations += 1
        self._prune_locked()
        self._commit_manifest()
        self._gen += 1
        self._active_bytes = 0
        self._active_records = 0
        self._active_t0 = self._active_t1 = None
        self._f = open(self._active_path(), "ab")

    def _prune_locked(self) -> None:
        total = sum(int(s.get("bytes") or 0) for s in self._segments)
        while len(self._segments) > 1 and total > self._max_bytes:
            old = self._segments.pop(0)
            total -= int(old.get("bytes") or 0)
            self.prunes += 1
            try:
                os.unlink(os.path.join(self.dir, old["file"]))
            except OSError:
                pass

    def _commit_manifest(self) -> None:
        doc = {"version": 1, "segments": self._segments}
        tmp = os.path.join(self.dir, _MANIFEST + ".tmp")
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.dir, _MANIFEST))
            _fsync_dir(self.dir)
        except OSError:
            pass

    # -- windowed queries --------------------------------------------------

    def records(self, t0: Optional[float] = None,
                t1: Optional[float] = None) -> List[dict]:
        """Records with ``t0 <= t <= t1`` (None = unbounded).  Served
        from the in-memory tail when it covers the span; otherwise the
        committed segments are re-read — the disk IS the source of
        truth, the tail only an accelerator."""
        with self._lock:
            tail = list(self._tail)
        lo = -float("inf") if t0 is None else float(t0)
        hi = float("inf") if t1 is None else float(t1)
        if tail and (self._t_first is None or tail[0][0] <= lo
                     or tail[0][0] <= (self._t_first or 0.0)):
            return [rec for t, rec in tail if lo <= t <= hi]
        doc = read_timeline(self.dir)
        return [rec for rec in doc["records"]
                if lo <= float(rec.get("t", 0.0)) <= hi]

    def merged_buckets(self, key: str, t0: Optional[float] = None,
                       t1: Optional[float] = None) -> dict:
        out: dict = {}
        for rec in self.records(t0, t1):
            d = (rec.get("hist") or {}).get(key)
            if d:
                out = merge_bucket_dicts(out, d)
        return out

    def percentile(self, key: str, q: float, t0: Optional[float] = None,
                   t1: Optional[float] = None) -> Optional[float]:
        """Percentile of ``key``'s distribution over [t0, t1], re-derived
        from the stored per-sweep bucket deltas — bit-consistent with the
        live rollup window by construction (same deltas, same
        ``merge_bucket_dicts`` + ``bucket_percentile``)."""
        merged = self.merged_buckets(key, t0, t1)
        if not any(merged.values()):
            return None
        return bucket_percentile(merged, q)

    def rate(self, key: str, window_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Windowed rate of a cumulative counter (events/s over the
        trailing ``window_s``) — the smoothed twin of the rollup's
        instantaneous scrape-to-scrape QPS, and what the autopilot's
        idle rules read so one quiet sweep cannot read as idleness.
        None before the store has any coverage."""
        now = time.monotonic() if now is None else float(now)
        t0 = now - float(window_s)
        total = 0
        seen = False
        for rec in self.records(t0, now):
            seen = True
            total += int((rec.get("counters") or {}).get(key, 0))
        if not seen:
            return None
        span = float(window_s)
        if self._t_first is not None:
            span = min(span, max(now - self._t_first, 0.0))
        if span <= 0.0:
            return None
        return total / span

    def series(self, gauge: str, t0: Optional[float] = None,
               t1: Optional[float] = None) -> List[Tuple[float, float]]:
        """(t, value) points of one gauge — what ``obs_top --timeline``
        renders as a sparkline."""
        out: List[Tuple[float, float]] = []
        for rec in self.records(t0, t1):
            v = (rec.get("gauges") or {}).get(gauge)
            if v is not None:
                out.append((float(rec.get("t", 0.0)), float(v)))
        return out

    def exemplar(self, key: str, edge: Optional[str] = None,
                 t0: Optional[float] = None,
                 t1: Optional[float] = None) -> Optional[int]:
        """Newest stored exemplar trace id for ``key`` (``serving`` /
        ``replay_op`` / ``inference_rtt``); ``edge`` narrows to one
        bucket (e.g. the bucket a p99 resolves to)."""
        for rec in reversed(self.records(t0, t1)):
            ex = (rec.get("exemplars") or {}).get(key)
            if not ex:
                continue
            if edge is None:
                return int(next(reversed(list(ex.values()))))
            if edge in ex:
                return int(ex[edge])
        return None

    # -- SLO rebuild -------------------------------------------------------

    def rebuild_slo(self, engine, now: Optional[float] = None) -> int:
        """Refill a (fresh) SLO engine's burn windows and rule states
        from the timeline tail — the aggregator-respawn story: without
        this a restarted engine opens a blind window in state ``ok`` and
        a live breach silently clears.  Rules are matched by name;
        returns how many got samples.  No events are emitted — the
        rebuild restores state, transitions stay the evaluator's job."""
        now = time.monotonic() if now is None else float(now)
        recs = self.records(now - float(engine.window_s), now)
        newest_state: Dict[str, str] = {}
        newest_value: Dict[str, float] = {}
        filled = 0
        for rule in engine.rules:
            window: List[Tuple[float, bool]] = []
            for rec in recs:
                ent = (rec.get("slo") or {}).get(rule.name)
                if not ent:
                    continue
                newest_state[rule.name] = ent.get("s", "ok")
                if ent.get("v") is not None:
                    newest_value[rule.name] = float(ent["v"])
                    window.append((float(rec.get("t", 0.0)),
                                   bool(ent.get("x"))))
            if not window and rule.name not in newest_state:
                continue
            rule._window.clear()
            rule._window.extend(window)
            if rule.name in newest_state:
                rule.state = newest_state[rule.name]
            if rule.name in newest_value:
                rule.last_value = newest_value[rule.name]
            filled += 1
        if filled:
            self.rebuilds += 1
        return filled

    # -- observability / lifecycle ----------------------------------------

    def stats(self) -> dict:
        """The ``timeline`` /varz section (docs/METRICS.md "Timeline
        schema")."""
        with self._lock:
            segments = list(self._segments)
            tail_n = len(self._tail)
            t_last = self._tail[-1][0] if self._tail else None
            active_bytes = self._active_bytes
            active_records = self._active_records
        committed_bytes = sum(int(s.get("bytes") or 0) for s in segments)
        return {
            "dir": self.dir,
            "gen": self._gen,
            "segments": len(segments),
            "records": sum(int(s.get("records") or 0) for s in segments)
            + active_records,
            "bytes": committed_bytes + active_bytes,
            "max_bytes": self._max_bytes,
            "appends": self.appends,
            "rotations": self.rotations,
            "prunes": self.prunes,
            "torn_records": self.torn_records,
            "adopted_records": self.adopted_records,
            "rebuilds": self.rebuilds,
            "tail_records": tail_n,
            "t_first": self._t_first,
            "t_last": t_last,
        }

    def close(self) -> None:
        """Commit the active segment — a clean shutdown leaves no
        uncommitted tail for the next incarnation to adopt."""
        with self._lock:
            if self._f is None:
                return
            if self._active_records:
                self._rotate_locked()
            try:
                self._f.close()
            except (OSError, ValueError):
                pass
            try:
                if self._active_records == 0:
                    os.unlink(self._active_path())
            except OSError:
                pass
            self._f = None
