"""The /metrics + /varz + /healthz exporter — a stdlib HTTP thread.

Reverb and friends ship a first-class metrics endpoint; this is ours,
with zero dependencies: a daemon ``ThreadingHTTPServer`` the trainer
(runtime/async_pipeline) and the serving front-end (serve.py) both
attach.  Endpoints:

  * ``/metrics`` — Prometheus text exposition from the registry
    (counters/gauges/histogram quantiles + flattened provider dicts).
    Exposition correctness is pinned by tests/test_timeline.py: the
    format's exact non-finite spellings (``NaN``/``+Inf``/``-Inf``,
    never python's ``nan``/``inf``), HELP text with newlines and
    backslashes escaped onto one line, and every summary shipping
    ``_sum`` alongside ``_count`` with quantiles in order.
  * ``/varz``    — the full JSON snapshot (what ``tools/obs_top.py``
    scrapes).  ``?trace=1`` additionally fires the on-demand
    ``jax.profiler`` hook (obs/trace.py) and reports its status inline.
  * ``/healthz`` — per-component liveness (HTTP 200 ok / 503 degraded):
    learner loop, ingest pump, checkpoint writer, serving batcher —
    whatever the host process registered.

Port 0 binds an ephemeral port (CI smoke gates); the bound port is on
``ObsServer.port``.  Binding is localhost by default — this is an
operator surface, not a public one.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse

from ape_x_dqn_tpu.obs.registry import Health, MetricsRegistry


class ObsServer:
    """One exporter thread over a registry (+ optional health + trace
    hook).  ``close()`` shuts the socket down; the thread is a daemon so
    a crashed host process never hangs on it."""

    def __init__(self, registry: MetricsRegistry,
                 health: Optional[Health] = None, port: int = 0,
                 host: str = "127.0.0.1",
                 trace_hook: Optional[Callable[..., dict]] = None):
        self.registry = registry
        self.health = health
        self._trace_hook = trace_hook
        obs = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # noqa: N802 — http.server API
                pass  # scrapes must not spam the metrics stream

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                try:
                    url = urlparse(self.path)
                    if url.path == "/metrics":
                        body = obs.registry.prometheus_text().encode()
                        self._reply(
                            200, body, "text/plain; version=0.0.4"
                        )
                    elif url.path == "/varz":
                        snap = obs.registry.snapshot()
                        q = parse_qs(url.query)
                        if q.get("trace", ["0"])[0] not in ("0", ""):
                            snap["trace"] = obs.trigger_trace(
                                steps=int(q["steps"][0])
                                if "steps" in q else None
                            )
                        body = json.dumps(snap, default=str).encode()
                        self._reply(200, body, "application/json")
                    elif url.path == "/healthz":
                        if obs.health is None:
                            st = {"status": "ok", "components": {}}
                        else:
                            st = obs.health.status()
                        code = 200 if st["status"] == "ok" else 503
                        self._reply(
                            code, json.dumps(st).encode(),
                            "application/json",
                        )
                    else:
                        self._reply(404, b"not found\n", "text/plain")
                except BrokenPipeError:
                    pass  # scraper went away mid-reply
                except Exception as e:  # noqa: BLE001 — always reply
                    try:
                        self._reply(
                            500,
                            f"{type(e).__name__}: {e}\n".encode(),
                            "text/plain",
                        )
                    except OSError:
                        pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="obs-exporter",
            daemon=True,
        )
        self._thread.start()

    def trigger_trace(self, steps: Optional[int] = None) -> dict:
        if self._trace_hook is None:
            return {"state": "unavailable",
                    "reason": "no trace hook attached"}
        try:
            return self._trace_hook(steps=steps)
        except Exception as e:  # noqa: BLE001 — scrape must not crash
            return {"state": "error", "reason": f"{type(e).__name__}: {e}"}

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
