"""Experience lineage tracing: follow a sampled chunk from the actor's
flush to the train step that consumed it.

The Ape-X paper's own analysis (age of experience at sample time,
priority staleness) needs per-transition provenance the pipeline never
had: a chunk crosses four hand-offs (actor flush → shm ring → replay
ingest → prioritized sample → train step) and until now the only
timestamp that survived was the transport's ``sent_t``.  This tracker
closes the loop:

  * **Trace IDs** — the actor stamps a random 63-bit id on a sampled
    fraction of chunks (``obs.trace_sample_rate``); the id rides the wire
    envelope (runtime/shm_ring ``_MSG``), costs 8 bytes per CHUNK (one
    flush of a whole fleet slice), and zero when unsampled.
  * **Spans** — ``on_ingest`` (ring drained into the replay),
    ``on_sample`` (slot indices of a learner batch), ``on_trained``
    (deferred priority write-back — the step's device work is done).
    A completed trace emits one ``lineage_span`` JSONL event with
    monotone CLOCK_MONOTONIC timestamps (comparable across processes on
    one host — the transport's documented clock discipline).
  * **Age of experience** — independent of sampling, every ingested
    slot's birth time is kept (8 bytes × capacity), and every sampled
    batch records its true ages into a log-bucketed histogram: the
    paper's age-at-sample distribution, measured — not inferred from
    cursor arithmetic.

Host-replay path only by design: the fused HBM replay never surfaces
sample indices to the host (that is the point of it), so lineage there
ends at ingest.

Thread-safety: ``on_ingest`` runs on the actor pump thread, ``on_sample``
/ ``on_trained`` on the learner thread — one lock, batched calls only.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ape_x_dqn_tpu.utils.metrics import LatencyHistogram

# Span keys in hand-off order; monotonicity over this order is the
# contract tests pin.
SPAN_ORDER = ("t_act", "t_ingest", "t_first_sample", "t_trained")


class TraceSpanLog:
    """Bounded per-process log of CROSS-TIER trace spans.

    PR 4's lineage follows an experience through ONE process's hand-offs;
    the RPC planes (replay service, central inference, serving net) cross
    process boundaries where the trace used to die.  Every participant —
    RPC client, shard server, serving front end, worker — records its hop
    here: ``{trace_id, hop, pid, t0_s, t1_s, dur_ms, ...}`` with
    CLOCK_MONOTONIC stamps (comparable across processes on one host —
    the transport's documented clock discipline; cross-host spans are
    skew-bounded like lineage's).  The fleet aggregator (obs/fleet.py)
    collects each process's recent spans off /varz or the shard ``stats``
    RPC and groups them by trace id into end-to-end timelines.

    Thread-safe; stdlib-only by design (shard servers and worker children
    construct one before jax exists)."""

    def __init__(self, depth: int = 128, emit=None, recorder=None):
        self._spans: deque = deque(maxlen=int(depth))
        self._emit = emit          # callable(name, **fields) — JSONL events
        self._recorder = recorder  # FlightRecorder mirror (shm-ring reach)
        self._lock = threading.Lock()
        self.recorded = 0

    def record(self, trace_id: int, hop: str, t0: float,
               t1: Optional[float] = None, **meta) -> Optional[dict]:
        """One completed hop span; no-op (None) when ``trace_id`` is 0 —
        call sites stay unconditional, the sample gate lives here."""
        if not trace_id:
            return None
        import os as _os

        t1 = float(t1 if t1 is not None else time.monotonic())
        span = {
            "trace_id": int(trace_id), "hop": hop, "pid": _os.getpid(),
            "t0_s": round(float(t0), 6), "t1_s": round(t1, 6),
            "dur_ms": round((t1 - float(t0)) * 1e3, 3), **meta,
        }
        with self._lock:
            self._spans.append(span)
            self.recorded += 1
        if self._recorder is not None:
            try:
                self._recorder.record("trace_span", **span)
            except Exception:  # noqa: BLE001 — tracing must not kill a run
                pass
        if self._emit is not None:
            try:
                self._emit("trace_span", **span)
            except Exception:  # noqa: BLE001 — tracing must not kill a run
                pass
        return span

    def snapshot(self) -> dict:
        """The ``trace_spans`` /varz shape: recent spans + the cumulative
        count (the aggregator's dedup key is (pid, trace_id, hop, t0_s))."""
        with self._lock:
            return {"recorded": self.recorded, "spans": list(self._spans)}


class BucketExemplars:
    """Newest sampled trace id per latency-histogram bucket.

    The timeline store (obs/timeline.py) can say *that* a p99 spike
    happened; an exemplar says *which request* — a ``trace_id`` whose
    assembled cross-tier timeline shows where the milliseconds went.
    Each record site that feeds a :class:`LatencyHistogram` mirrors the
    traced fraction of its samples here, keyed to the SAME bucket edge
    the count landed in (``LatencyHistogram.bucket_edge``), newest id
    per bucket winning — Prometheus' OpenMetrics exemplar semantics,
    without the exposition format.  A p99 query resolves its percentile
    to a bucket edge, looks the edge up here, and hands the id to the
    aggregator's trace timelines.

    Thread-safe, bounded (one id per non-empty bucket, LRU past
    ``max_buckets``), and free when tracing is off: a zero trace id is
    a no-op, exactly the :class:`TraceSpanLog` gate."""

    def __init__(self, hist, max_buckets: int = 64):
        self._hist = hist
        self._max = int(max_buckets)
        self._by_edge: "dict[str, int]" = {}
        self._order: deque = deque()
        self._lock = threading.Lock()
        self.recorded = 0

    def record(self, seconds: float, trace_id: int) -> None:
        if not trace_id:
            return
        edge = self._hist.bucket_edge(seconds)
        with self._lock:
            if edge not in self._by_edge:
                self._order.append(edge)
                while len(self._order) > self._max:
                    self._by_edge.pop(self._order.popleft(), None)
            self._by_edge[edge] = int(trace_id)
            self.recorded += 1

    def snapshot(self) -> Dict[str, int]:
        """{bucket_edge_label: newest trace_id} — rides the owning
        surface's stats dict so the aggregator can lift it fleet-wide."""
        with self._lock:
            return dict(self._by_edge)


class LineageTracker:
    def __init__(self, capacity: int, emit=None, max_open_traces: int = 512,
                 keep_completed: int = 16):
        self.capacity = int(capacity)
        self._emit = emit  # callable(name, **fields) — MetricLogger.event
        self._birth = np.zeros(self.capacity, np.float64)  # 0 = never filled
        self._traced = np.zeros(self.capacity, bool)
        self._slot_trace: Dict[int, int] = {}   # slot -> open trace id
        self._open: "dict[int, dict]" = {}
        self._max_open = int(max_open_traces)
        self._completed: deque = deque(maxlen=int(keep_completed))
        self.completed_count = 0
        self.abandoned_count = 0   # slots recycled before the trace closed
        # Monotone-clock guard (cross-host fleets): a chunk's wire
        # ``sent_t`` is CLOCK_MONOTONIC on the PRODUCER's host, which is
        # only comparable here when producer and consumer share a host.
        # A remote worker's clock can run ahead, making t_act land in our
        # future and the act→ingest span negative; such stamps are
        # clamped to ingest time and counted (the
        # ``lineage/clock_skew_clamped`` observable — a nonzero value
        # means cross-host spans are skew-bounded, not exact).
        self.clock_skew_clamped = 0
        self._lock = threading.Lock()
        # True age at sample time, seconds (ms fields in the summary).
        self.age_hist = LatencyHistogram(min_s=1e-3, max_s=7200.0,
                                         per_decade=10)
        self.span_hists = {
            "act_to_ingest": LatencyHistogram(min_s=1e-4, max_s=3600.0),
            "ingest_to_first_sample": LatencyHistogram(min_s=1e-4,
                                                       max_s=7200.0),
            "act_to_trained": LatencyHistogram(min_s=1e-4, max_s=7200.0),
        }

    # -- hand-off hooks ----------------------------------------------------

    def on_ingest(self, indices, t_act: Optional[float] = None,
                  trace_id: int = 0, wid: Optional[int] = None) -> None:
        """A chunk landed in replay slots ``indices`` (the array
        ``PrioritizedReplay.add`` returned).  ``t_act`` is the producer's
        send time (wire ``sent_t``); ``trace_id`` nonzero marks the chunk
        traced."""
        idx = np.asarray(indices, np.int64)
        if idx.size == 0:
            return
        now = time.monotonic()
        if t_act is not None and t_act > now:
            # Clock skew (remote producer's monotonic clock runs ahead):
            # clamp at zero age rather than emit a negative span.
            t_act = now
            with self._lock:
                self.clock_skew_clamped += 1
        with self._lock:
            # Recycled slots first: an overwrite before the old trace
            # completed abandons it (the transition is gone — that IS the
            # finding, not an error).
            if self._traced[idx].any():
                for s in idx[self._traced[idx]]:
                    self._abandon_slot_locked(int(s))
            self._birth[idx] = now
            if trace_id:
                if len(self._open) >= self._max_open:
                    oldest = next(iter(self._open))
                    self._drop_trace_locked(oldest, abandoned=True)
                self._open[int(trace_id)] = {
                    "trace_id": int(trace_id),
                    "wid": wid,
                    "slots": idx.copy(),
                    "t_act": float(t_act) if t_act is not None else now,
                    "t_ingest": now,
                    "rows": int(idx.size),
                }
                self._traced[idx] = True
                for s in idx:
                    self._slot_trace[int(s)] = int(trace_id)

    def on_sample(self, indices) -> None:
        """A prioritized batch was sampled at these replay slots."""
        idx = np.asarray(indices, np.int64)
        if idx.size == 0:
            return
        now = time.monotonic()
        births = self._birth[idx]
        for age in (now - births[births > 0.0]):
            self.age_hist.record(float(age))
        if not self._traced[idx].any():
            return
        with self._lock:
            for s in idx[self._traced[idx]]:
                rec = self._open.get(self._slot_trace.get(int(s), -1))
                if rec is not None and "t_first_sample" not in rec:
                    rec["t_first_sample"] = now

    def on_trained(self, indices) -> None:
        """The train step that consumed these slots has completed (the
        deferred priority write-back point — its device work is forced)."""
        idx = np.asarray(indices, np.int64)
        if idx.size == 0 or not self._traced[idx].any():
            return
        now = time.monotonic()
        done: List[dict] = []
        with self._lock:
            for s in idx[self._traced[idx]]:
                tid = self._slot_trace.get(int(s))
                rec = self._open.get(tid) if tid is not None else None
                if rec is None or "t_first_sample" not in rec:
                    continue  # trained before sampled can't happen; guard
                rec["t_trained"] = now
                self._drop_trace_locked(tid, abandoned=False)
                done.append(rec)
        for rec in done:
            self._complete(rec)

    def trace_ids_for(self, indices) -> List[int]:
        """Open trace ids among these replay slots (deduped, first-seen
        order) — how the learner tags a sample / priority-write-back RPC
        span with the trace of an experience it touched."""
        idx = np.asarray(indices, np.int64)
        if idx.size == 0 or not self._traced[idx].any():
            return []
        out: List[int] = []
        with self._lock:
            for s in idx[self._traced[idx]]:
                tid = self._slot_trace.get(int(s))
                if tid is not None and tid not in out:
                    out.append(tid)
        return out

    # -- internals ---------------------------------------------------------

    def _abandon_slot_locked(self, slot: int) -> None:
        tid = self._slot_trace.get(slot)
        if tid is not None and tid in self._open:
            self._drop_trace_locked(tid, abandoned=True)

    def _drop_trace_locked(self, trace_id: int, abandoned: bool) -> None:
        rec = self._open.pop(trace_id, None)
        if rec is None:
            return
        slots = rec["slots"]
        self._traced[slots] = False
        for s in slots:
            self._slot_trace.pop(int(s), None)
        if abandoned:
            self.abandoned_count += 1

    def _complete(self, rec: dict) -> None:
        spans = {
            "act_to_ingest_ms": (rec["t_ingest"] - rec["t_act"]) * 1e3,
            "ingest_to_first_sample_ms":
                (rec["t_first_sample"] - rec["t_ingest"]) * 1e3,
            "first_sample_to_trained_ms":
                (rec["t_trained"] - rec["t_first_sample"]) * 1e3,
            "act_to_trained_ms": (rec["t_trained"] - rec["t_act"]) * 1e3,
        }
        self.span_hists["act_to_ingest"].record(
            max(0.0, rec["t_ingest"] - rec["t_act"])
        )
        self.span_hists["ingest_to_first_sample"].record(
            max(0.0, rec["t_first_sample"] - rec["t_ingest"])
        )
        self.span_hists["act_to_trained"].record(
            max(0.0, rec["t_trained"] - rec["t_act"])
        )
        event = {
            "trace_id": rec["trace_id"],
            "wid": rec["wid"],
            "rows": rec["rows"],
            **{k: round(rec[k], 6) for k in SPAN_ORDER},
            **{k: round(v, 3) for k, v in spans.items()},
        }
        self.completed_count += 1
        self._completed.append(event)
        if self._emit is not None:
            try:
                self._emit("lineage_span", **event)
            except Exception:  # noqa: BLE001 — tracing must not kill a run
                pass

    # -- snapshot ----------------------------------------------------------

    def summary(self, include_recent: bool = True) -> dict:
        """The /varz + JSONL lineage section: true age-of-experience
        distribution at sample time plus span percentiles.  The JSONL
        emit passes ``include_recent=False`` — completed spans already
        ride the stream as their own ``lineage_span`` events."""
        with self._lock:
            open_n = len(self._open)
        age = self.age_hist.summary()
        age["buckets_s"] = self.age_hist.buckets()
        out = {
            "age_at_sample": age,
            "spans_ms": {
                k: h.summary() for k, h in self.span_hists.items()
                if h.count
            },
            "traces_open": open_n,
            "traces_completed": self.completed_count,
            "traces_abandoned": self.abandoned_count,
            "clock_skew_clamped": self.clock_skew_clamped,
        }
        if include_recent:
            out["recent_spans"] = list(self._completed)
        return out
