"""Cross-process metrics registry: typed instruments + snapshot + export.

The repo grew four runtime tiers (process actors, the fused learner, the
async checkpoint writer, the serving tier) and each invented its own
ad-hoc JSONL fragment.  This registry is the shared schema they plug
into: typed **counters / gauges / histograms** built on the proven
primitives in ``utils/metrics`` (``RateCounter`` windows,
``LatencyHistogram`` log buckets), plus **providers** — callables whose
dict snapshots fold in the stats surfaces that already exist
(``ProcessActorPool.transport_stats``, ``IncrementalCheckpointer.stats``,
``PolicyServer.stats``, per-worker shm stats blocks) without rewriting
them.  One ``snapshot()`` is the /varz JSON, one ``prometheus_text()``
is the /metrics scrape (obs/exporter.py), and the same dict rides the
JSONL emit — three views, one source of truth.

``Health`` is the /healthz source: components **beat** (learner loop,
ingest pump) or register an **age function** (threads that already track
a last-activity time); a heartbeat older than ``stale_after_s`` marks
the component — and the whole process — degraded.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Callable, Dict, Optional

from ape_x_dqn_tpu.utils.metrics import LatencyHistogram, RateCounter


class Counter:
    """Monotone counter with a sliding-window rate (events/s)."""

    kind = "counter"

    def __init__(self, help: str = "", window_s: float = 30.0):
        self.help = help
        self._value = 0.0
        self._rate = RateCounter(window_s)
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up — use a Gauge")
        with self._lock:
            self._value += n
        self._rate.add(n)

    def merge(self, other: "Counter") -> None:
        """Fold another counter in: totals add, rate windows interleave
        (the rollup discipline — associative/commutative like every
        merge in this module)."""
        with other._lock:
            value = other._value
        with self._lock:
            self._value += value
        self._rate.merge(other._rate)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def rate(self) -> float:
        return self._rate.rate()

    def snapshot(self):
        return {"total": self.value, "rate_s": round(self.rate(), 3)}


class Gauge:
    """Last-write-wins scalar.  A float attribute store is atomic under
    CPython, so reads need no lock; ``set_fn`` turns it into a computed
    gauge evaluated at snapshot time."""

    kind = "gauge"

    def __init__(self, help: str = ""):
        self.help = help
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None

    def set(self, value: float) -> None:
        self._value = float(value)

    def set_fn(self, fn: Callable[[], float]) -> None:
        self._fn = fn

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a scrape must never crash
                return float("nan")
        return self._value

    def snapshot(self):
        return self.value

    def merge(self, other: "Gauge") -> None:
        """Fold another gauge in: last-write-wins has no cross-process
        order, so the merge takes the MAX (the conservative rollup for
        occupancy/backlog-style gauges; max is associative/commutative
        where picking either side is not).  Computed gauges merge by
        value at merge time."""
        self._value = max(self.value, other.value)
        self._fn = None   # the merged value is a plain scalar now


class Histogram:
    """Log-bucketed distribution (``utils.metrics.LatencyHistogram``):
    O(1) observe on hot paths, percentile summary + raw buckets out."""

    kind = "histogram"

    def __init__(self, help: str = "", min_s: float = 1e-5,
                 max_s: float = 120.0, per_decade: int = 20):
        self.help = help
        self._hist = LatencyHistogram(
            min_s=min_s, max_s=max_s, per_decade=per_decade
        )

    def observe(self, value: float) -> None:
        self._hist.record(value)

    @property
    def count(self) -> int:
        return self._hist.count

    @property
    def sum(self) -> float:
        """Total observed seconds (the ``_sum`` series a Prometheus
        summary exposes next to ``_count``)."""
        return float(self._hist._sum)

    def percentile(self, p: float) -> float:
        return self._hist.percentile(p)

    def merge(self, other: "Histogram") -> None:
        """Bucket-wise fold (``LatencyHistogram.merge`` — layouts must
        match or it raises; silent misalignment would corrupt
        percentiles)."""
        self._hist.merge(other._hist)

    def snapshot(self):
        out = self._hist.summary()
        out["buckets"] = self._hist.buckets()
        return out


_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(*parts: str) -> str:
    return _NAME_RE.sub("_", "_".join(p for p in parts if p))


def _prom_value(v: float) -> str:
    """Prometheus sample-value rendering: the text format spells the
    specials ``+Inf`` / ``-Inf`` / ``NaN`` — Python's ``{:g}`` renders
    ``inf`` / ``nan``, which scrapers reject as unparseable lines."""
    v = float(v)
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    return f"{v:g}"


def _prom_help(text: str) -> str:
    """HELP-line escaping per the exposition format: backslash and
    newline would otherwise break the line protocol."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _flatten(prefix: str, value, out: list) -> None:
    """Numeric leaves of a nested snapshot dict → (name, value) pairs —
    how provider dicts (transport stats, worker sweeps) become scrapeable
    series without per-source schemas."""
    if isinstance(value, bool):
        out.append((prefix, int(value)))
    elif isinstance(value, (int, float)):
        out.append((prefix, value))
    elif isinstance(value, dict):
        for k, v in value.items():
            _flatten(_prom_name(prefix, str(k)), v, out)


class MetricsRegistry:
    """Named typed instruments + pluggable snapshot providers."""

    def __init__(self, prefix: str = "apex"):
        self.prefix = prefix
        self._instruments: Dict[str, object] = {}
        self._providers: Dict[str, Callable[[], dict]] = {}
        self._lock = threading.Lock()

    # -- instruments -------------------------------------------------------

    def _get_or_create(self, name: str, cls, **kwargs):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls(**kwargs)
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {inst.kind}"
                )
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help=help)

    def histogram(self, name: str, help: str = "", min_s: float = 1e-5,
                  max_s: float = 120.0, per_decade: int = 20) -> Histogram:
        return self._get_or_create(
            name, Histogram, help=help, min_s=min_s, max_s=max_s,
            per_decade=per_decade,
        )

    # -- providers ---------------------------------------------------------

    def register_provider(self, name: str, fn: Callable[[], dict]) -> None:
        """Fold ``fn()``'s dict into every snapshot under ``name`` — the
        adapter for stats surfaces that already exist elsewhere."""
        with self._lock:
            self._providers[name] = fn

    def unregister_provider(self, name: str) -> None:
        with self._lock:
            self._providers.pop(name, None)

    # -- views -------------------------------------------------------------

    def snapshot(self) -> dict:
        """The /varz JSON: typed instruments under their names, provider
        dicts under theirs.  Provider failures degrade to an ``error``
        entry — a half-dead run is exactly when a scrape matters most."""
        with self._lock:
            instruments = dict(self._instruments)
            providers = dict(self._providers)
        out: dict = {"t_mono": round(time.monotonic(), 3)}
        for name, inst in instruments.items():
            out[name] = inst.snapshot()
        for name, fn in providers.items():
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001 — scrape must not crash
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def prometheus_text(self) -> str:
        """Prometheus text exposition: typed instruments natively
        (counter total, gauge value, histogram quantile series +
        _count/_sum), provider dicts flattened to numeric-leaf gauges."""
        with self._lock:
            instruments = dict(self._instruments)
            providers = dict(self._providers)
        lines: list = []
        for name, inst in sorted(instruments.items()):
            pname = _prom_name(self.prefix, name)
            if inst.help:
                lines.append(f"# HELP {pname} {_prom_help(inst.help)}")
            if isinstance(inst, Counter):
                lines.append(f"# TYPE {pname} counter")
                lines.append(f"{pname}_total {_prom_value(inst.value)}")
            elif isinstance(inst, Gauge):
                lines.append(f"# TYPE {pname} gauge")
                lines.append(f"{pname} {_prom_value(inst.value)}")
            elif isinstance(inst, Histogram):
                lines.append(f"# TYPE {pname} summary")
                for q in (0.5, 0.95, 0.99):
                    v = inst.percentile(q * 100)
                    v = v if v == v else 0.0  # NaN (empty) → 0
                    lines.append(
                        f'{pname}{{quantile="{q}"}} {_prom_value(v)}'
                    )
                lines.append(f"{pname}_sum {_prom_value(inst.sum)}")
                lines.append(f"{pname}_count {inst.count}")
        flat: list = []
        for name, fn in sorted(providers.items()):
            try:
                _flatten(_prom_name(self.prefix, name), fn(), flat)
            except Exception:  # noqa: BLE001 — scrape must not crash
                continue
        for pname, value in flat:
            lines.append(f"{pname} {_prom_value(value)}")
        return "\n".join(lines) + "\n"


class Health:
    """Per-component liveness for /healthz.

    ``beat(name)`` for loops that can call in; ``register(name, age_fn)``
    for components that already track their own last-activity time.
    ``status()`` marks any component whose age exceeds ``stale_after_s``
    (overridable per component) degraded, and the process with it.
    """

    def __init__(self, stale_after_s: float = 15.0):
        self.stale_after_s = float(stale_after_s)
        self._beats: Dict[str, float] = {}
        self._age_fns: Dict[str, Callable[[], float]] = {}
        self._stale: Dict[str, float] = {}
        self._lock = threading.Lock()

    def beat(self, name: str) -> None:
        with self._lock:
            self._beats[name] = time.monotonic()

    def register(self, name: str, age_fn: Callable[[], float],
                 stale_after_s: Optional[float] = None) -> None:
        with self._lock:
            self._age_fns[name] = age_fn
            if stale_after_s is not None:
                self._stale[name] = float(stale_after_s)

    def merge(self, other: "Health") -> None:
        """Fold another Health in: component sets union; a component both
        sides track keeps its FRESHEST beat (max timestamp = min age —
        associative/commutative, so fold order never changes status()),
        and the tighter per-component staleness bound wins.  Age
        functions ride through where this side has none (a merged view
        keeps watching live sources)."""
        with other._lock:
            beats = dict(other._beats)
            age_fns = dict(other._age_fns)
            stale = dict(other._stale)
        with self._lock:
            for name, t in beats.items():
                self._beats[name] = max(self._beats.get(name, t), t)
            for name, fn in age_fns.items():
                self._age_fns.setdefault(name, fn)
            for name, bound in stale.items():
                self._stale[name] = min(self._stale.get(name, bound), bound)

    def status(self) -> dict:
        now = time.monotonic()
        with self._lock:
            beats = dict(self._beats)
            age_fns = dict(self._age_fns)
            stale = dict(self._stale)
        components: dict = {}
        ok_all = True
        for name, t in beats.items():
            age = now - t
            ok = age <= stale.get(name, self.stale_after_s)
            components[name] = {"age_s": round(age, 3), "ok": ok}
            ok_all &= ok
        for name, fn in age_fns.items():
            try:
                age = float(fn())
            except Exception:  # noqa: BLE001 — a dead age fn IS degraded
                age = float("inf")
            ok = age <= stale.get(name, self.stale_after_s)
            components[name] = {"age_s": round(min(age, 1e12), 3), "ok": ok}
            ok_all &= ok
        return {
            "status": "ok" if ok_all else "degraded",
            "components": components,
        }
