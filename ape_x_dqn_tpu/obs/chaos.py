"""Deterministic, seed-driven fault injection — the chaos half of the
fault-tolerance contract.

The supervision tier (runtime/supervisor.py) claims the fleet survives
any single component dying; this module is how that claim gets TESTED
instead of asserted.  Every fault the production postmortems have actually
seen has an injector here:

  * **SIGKILL / SIGSTOP a worker** — the process-actor death shapes the
    salvage + respawn discipline exists for.
  * **Torn shm-ring record** — an uncommitted record scribbled at a dead
    worker's write cursor: the deterministic twin of "killed mid-write"
    (the real kill only tears a record if it lands inside the microseconds
    of a ring write; the injector makes the torn-tail path run every time).
    Only ever applied to a ring whose writer is already dead — scribbling
    under a live writer would corrupt the SPSC discipline itself.
  * **Corrupted APXC chunk** — one byte flipped (or the file truncated) in
    a committed checkpoint chunk: the restore fallback's trigger.
  * **Stuck stager / slow env / /dev/shm pressure** — liveness and
    capacity faults: a gate the ingest stager polls, a latency wrapper
    around worker envs, a transient shared-memory allocation.

``ChaosMonkey`` sequences these on a schedule derived entirely from
``chaos.seed`` (config.ChaosConfig): same seed, same fault times, same
victims — a failing chaos soak reproduces.  All injectors are also usable
directly (tools/chaos_smoke.py drives them one by one).

Import-light by contract (stdlib + numpy + shm_ring): the latency wrapper
runs inside worker children before jax exists there.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import List, Optional

# ---------------------------------------------------------------------------
# One-shot injectors
# ---------------------------------------------------------------------------


def inject_torn_record(ring, garbage_bytes: int = 64,
                       rng: Optional[random.Random] = None) -> dict:
    """Scribble one STARTED-but-never-committed record at ``ring``'s write
    cursor — what a SIGKILL lands mid-``ShmRing.write`` leaves behind.

    Bumps the writer's ``started`` counter and writes a garbage header +
    payload with a non-matching commit word, so the reader's seq check
    rejects it forever and ``torn_tail()`` reports True at salvage.  The
    caller must guarantee the writer is DEAD (this writes into the ring's
    free region from outside the single-writer discipline).
    """
    from ape_x_dqn_tpu.runtime.shm_ring import _OFF_STARTED, _REC

    rng = rng or random.Random(0)
    started = ring._get(_OFF_STARTED)
    ring._set(_OFF_STARTED, started + 1)
    widx = ring.committed_bytes
    free = ring.capacity - (widx - ring._reader_cursor())
    n = max(0, min(int(garbage_bytes), free - _REC.size))
    if free >= _REC.size:
        # A plausible half-written frame: valid-looking length, garbage
        # crc, and a STALE seq (0 can never be the next expected record).
        ring._copy_in(widx, _REC.pack(n, rng.getrandbits(32), 0))
        if n:
            ring._copy_in(
                widx + _REC.size, bytes(rng.getrandbits(8) for _ in range(n))
            )
    return {"fault": "torn_record", "ring": ring.name,
            "started": started + 1, "garbage_bytes": n}


def corrupt_chunk(path: str, mode: str = "bitflip",
                  rng: Optional[random.Random] = None) -> dict:
    """Damage one committed chunk file in a detectable way.

    ``bitflip`` flips a single payload bit (CRC mismatch), ``truncate``
    cuts the file to header-only (truncated payload), ``zero`` empties it
    (truncated header).  All three must surface as ``ChunkCorrupt`` at
    read time — tests/test_chaos.py pins that.
    """
    rng = rng or random.Random(0)
    size = os.path.getsize(path)
    if mode == "bitflip":
        # Past the 20-byte APXC header so the flip lands in the payload.
        off = 20 + rng.randrange(max(1, size - 20)) if size > 20 else 0
        with open(path, "r+b") as f:
            f.seek(off)
            byte = f.read(1)
            f.seek(off)
            f.write(bytes([byte[0] ^ (1 << rng.randrange(8))]))
    elif mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(min(size, 20))
    elif mode == "zero":
        with open(path, "r+b") as f:
            f.truncate(0)
    else:
        raise ValueError(f"unknown corruption mode: {mode}")
    return {"fault": "corrupt_chunk", "path": path, "mode": mode,
            "orig_bytes": size}


def pick_chunk(inc_dir: str, rng: Optional[random.Random] = None,
               prefer: str = "any") -> Optional[str]:
    """A committed chunk file under one ``replay_inc*`` dir (seeded
    choice).  ``prefer`` narrows to ``"base"`` (``chunk_<G>_0``) or
    ``"delta"`` chunks of the manifest's live generation."""
    import json

    rng = rng or random.Random(0)
    manifest_path = os.path.join(inc_dir, "MANIFEST.json")
    if not os.path.exists(manifest_path):
        return None
    try:
        with open(manifest_path) as f:
            chunks = json.load(f)["chunks"]
    except (ValueError, KeyError, OSError):
        return None
    if prefer == "base":
        chunks = chunks[:1]
    elif prefer == "delta":
        chunks = chunks[1:]
    chunks = [c for c in chunks
              if os.path.exists(os.path.join(inc_dir, c))]
    if not chunks:
        return None
    return os.path.join(inc_dir, rng.choice(chunks))


class RpcChaos:
    """Seeded fault injection for the replay-service RPC plane
    (replay/service.py — config ``chaos.rpc_delay_ms`` /
    ``chaos.rpc_drop_rate``).

    Installed shard-side: ``delay_s()`` is consulted before every request
    executes (mean ``delay_ms`` with ±50% seeded jitter — sleeping the
    shard's pump thread IS the fault: every queued request behind it
    waits too, the slow-replay shape the client's deadline exists for);
    ``drop()`` decides whether a well-framed request is silently
    discarded (no reply — the lost-reply shape that forces the client's
    whole-request retry and proves the at-most-once add dedup).  Both
    streams are pure functions of the seed, so a failing run reproduces.
    """

    def __init__(self, delay_ms: float = 0.0, drop_rate: float = 0.0,
                 seed: int = 0):
        self.delay_ms = float(delay_ms)
        self.drop_rate = float(drop_rate)
        self._rng = random.Random(seed ^ 0x69C)
        self.delays = 0
        self.drops = 0

    def delay_s(self) -> float:
        if self.delay_ms <= 0:
            return 0.0
        self.delays += 1
        return self.delay_ms * (0.5 + self._rng.random()) / 1e3

    def drop(self) -> bool:
        if self.drop_rate <= 0:
            return False
        hit = self._rng.random() < self.drop_rate
        self.drops += int(hit)
        return hit


class SlowEnv:
    """Env wrapper injecting seeded per-step latency (the slow-emulator
    scenario).  Delegates everything else to the wrapped env."""

    def __init__(self, env, latency_s: float, seed: int = 0):
        self._env = env
        self._latency_s = float(latency_s)
        self._rng = random.Random(seed)

    def __getattr__(self, name):
        return getattr(self._env, name)

    def reset(self, *a, **kw):
        return self._env.reset(*a, **kw)

    def step(self, *a, **kw):
        # Mean latency_s with +/-50% seeded jitter: slow, not metronomic.
        time.sleep(self._latency_s * (0.5 + self._rng.random()))
        return self._env.step(*a, **kw)


class ShmFiller:
    """Transient /dev/shm pressure: allocate a shared-memory segment of
    ``nbytes`` and hold it until ``release()``.  Allocation failure is the
    fault succeeding differently (the filesystem is ALREADY exhausted) —
    reported, never raised."""

    def __init__(self):
        self._seg = None

    def fill(self, nbytes: int) -> dict:
        self.release()
        try:
            from ape_x_dqn_tpu.runtime.shm_ring import create_shared_memory

            self._seg = create_shared_memory("chaosfill", max(1, int(nbytes)))
            # Touch the pages so tmpfs actually commits them.
            self._seg.buf[::4096] = b"\xff" * len(self._seg.buf[::4096])
            return {"fault": "shm_fill", "bytes": int(nbytes),
                    "name": self._seg.name}
        except OSError as e:
            return {"fault": "shm_fill", "bytes": int(nbytes),
                    "failed": f"{type(e).__name__}: {e}"}

    def release(self) -> None:
        if self._seg is not None:
            try:
                self._seg.close()
                self._seg.unlink()
            except (OSError, FileNotFoundError):
                pass
            self._seg = None


# ---------------------------------------------------------------------------
# The scheduled monkey
# ---------------------------------------------------------------------------


class ChaosMonkey:
    """Seed-driven fault scheduler over one training run.

    Each enabled fault kind fires on its own cadence
    (``interval * (0.5 + u)`` between events, ``u`` from the seeded rng),
    merged into one deterministic timeline.  Victims (which worker, which
    chunk, which byte) come from the same rng, so the whole fault sequence
    is a pure function of ``(config, seed)``.

    Targets are late-bound: ``attach(pool=..., ckpt_dirs=...,
    stager_gate=...)`` — the tools construct the monkey before the
    pipeline exists.  Every executed fault lands in ``self.log`` (a
    bounded list of dicts), on the optional metrics registry
    (``chaos/<kind>`` counters), and through the optional ``emit``
    callback (the JSONL stream).
    """

    KINDS = ("kill", "sigstop", "torn_record", "corrupt_chunk",
             "stuck_stager", "shm_fill", "kill_shard")

    def __init__(self, cfg, registry=None, emit=None,
                 horizon_s: float = 3600.0):
        self.cfg = cfg
        self._emit = emit
        self.log: List[dict] = []
        self._counters = {}
        if registry is not None:
            for kind in self.KINDS:
                self._counters[kind] = registry.counter(
                    f"chaos/{kind}", help=f"injected {kind} faults"
                )
            registry.register_provider("chaos", self.state)
        self._rng = random.Random(int(cfg.seed) ^ 0xC4405)
        self.schedule = self._build_schedule(float(horizon_s))
        self._pool = None
        self._replay_fleet = None   # ReplayServiceFleet (kill_shard kind)
        self._ckpt_dirs: List[str] = []
        self._stager_stall = threading.Event()
        self._filler = ShmFiller()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0: Optional[float] = None

    # -- schedule (pure function of config + seed) -------------------------

    def _build_schedule(self, horizon_s: float) -> List[tuple]:
        intervals = {
            "kill": self.cfg.kill_interval_s,
            "sigstop": self.cfg.sigstop_interval_s,
            "torn_record": self.cfg.torn_record_interval_s,
            "corrupt_chunk": self.cfg.corrupt_chunk_interval_s,
            "stuck_stager": self.cfg.stuck_stager_interval_s,
            "shm_fill": self.cfg.shm_fill_interval_s,
            "kill_shard": getattr(self.cfg, "kill_shard_interval_s", 0.0),
        }
        events: List[tuple] = []
        for kind in self.KINDS:  # fixed order: determinism
            mean = float(intervals[kind])
            if mean <= 0:
                continue
            t = 0.0
            while True:
                t += mean * (0.5 + self._rng.random())
                if t > horizon_s:
                    break
                events.append((round(t, 4), kind))
        events.sort()
        return events

    # -- wiring ------------------------------------------------------------

    def attach(self, pool=None, ckpt_dirs=None,
               replay_fleet=None) -> "ChaosMonkey":
        self._pool = pool if pool is not None else self._pool
        if ckpt_dirs:
            self._ckpt_dirs = list(ckpt_dirs)
        if replay_fleet is not None:
            self._replay_fleet = replay_fleet
        return self

    def stager_stalled(self) -> bool:
        """Polled by the ingest stager's loop (the stuck-stager gate)."""
        return self._stager_stall.is_set()

    def state(self) -> dict:
        by_kind = {}
        for rec in self.log:
            by_kind[rec["fault"]] = by_kind.get(rec["fault"], 0) + 1
        return {
            "scheduled": len(self.schedule),
            "executed": len(self.log),
            "by_kind": by_kind,
            "stager_stalled": self._stager_stall.is_set(),
        }

    def counts(self) -> dict:
        return dict(self.state()["by_kind"])

    # -- execution ---------------------------------------------------------

    def start(self) -> "ChaosMonkey":
        if self._thread is None:
            self._t0 = time.monotonic()
            self._thread = threading.Thread(
                target=self._loop, name="chaos-monkey", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._stager_stall.clear()
        self._filler.release()
        if self._thread is not None:
            self._thread.join(timeout=10.0)

    def _loop(self) -> None:
        for t, kind in self.schedule:
            while not self._stop.is_set():
                delay = self._t0 + t - time.monotonic()
                if delay <= 0:
                    break
                time.sleep(min(delay, 0.1))
            if self._stop.is_set():
                return
            self.execute(kind)

    def _record(self, rec: dict) -> dict:
        rec = {"t": round(time.monotonic() - (self._t0 or 0.0), 3), **rec}
        self.log.append(rec)
        if len(self.log) > 4096:
            del self.log[:1024]
        c = self._counters.get(rec.get("fault"))
        if c is not None:
            c.inc()
        if self._emit is not None:
            try:
                self._emit("chaos_fault", **rec)
            except Exception:  # noqa: BLE001 — telemetry never blocks chaos
                pass
        return rec

    def _live_workers(self) -> List[tuple]:
        if self._pool is None:
            return []
        out = []
        for wid, p in enumerate(self._pool._procs):
            if p is not None and p.is_alive() and p.pid:
                out.append((wid, p))
        return out

    # Public so drivers (chaos_smoke / chaos_soak) can force individual
    # faults on top of — or instead of — the schedule.
    def execute(self, kind: str) -> Optional[dict]:
        try:
            if kind == "kill":
                return self._do_kill(torn=False)
            if kind == "torn_record":
                return self._do_kill(torn=True)
            if kind == "sigstop":
                return self._do_sigstop()
            if kind == "corrupt_chunk":
                return self._do_corrupt_chunk()
            if kind == "stuck_stager":
                return self._do_stuck_stager()
            if kind == "shm_fill":
                return self._do_shm_fill()
            if kind == "kill_shard":
                return self._do_kill_shard()
        except Exception as e:  # noqa: BLE001 — a failed injection is data
            return self._record(
                {"fault": kind, "failed": f"{type(e).__name__}: {e}"}
            )
        return None

    def _do_kill(self, torn: bool) -> Optional[dict]:
        victims = self._live_workers()
        if not victims:
            return self._record({"fault": "torn_record" if torn else "kill",
                                 "skipped": "no live workers"})
        wid, proc = victims[self._rng.randrange(len(victims))]
        ring = self._pool._rings.get(wid)  # THIS incarnation's ring
        os.kill(proc.pid, signal.SIGKILL)
        proc.join(timeout=10.0)  # the ring writer must be gone before we
        rec = {"fault": "kill", "worker": wid, "pid": proc.pid}
        if torn:
            # ... scribble its torn tail (dead-writer precondition) — but
            # only if the supervisor has not already salvaged + respawned:
            # the replacement ring has a LIVE writer, off limits.
            if ring is not None and self._pool._rings.get(wid) is ring:
                rec = {**inject_torn_record(ring, rng=self._rng),
                       "worker": wid, "pid": proc.pid}
            else:
                rec["torn_skipped"] = "incarnation already retired"
        return self._record(rec)

    def _do_sigstop(self) -> Optional[dict]:
        victims = self._live_workers()
        if not victims:
            return self._record({"fault": "sigstop",
                                 "skipped": "no live workers"})
        wid, proc = victims[self._rng.randrange(len(victims))]
        hold = float(self.cfg.sigstop_hold_s)
        try:
            os.kill(proc.pid, signal.SIGSTOP)
            self._stop.wait(hold)
        finally:
            try:
                os.kill(proc.pid, signal.SIGCONT)
            except ProcessLookupError:
                pass  # reaped while stopped (supervisor saw it dead)
        return self._record({"fault": "sigstop", "worker": wid,
                             "pid": proc.pid, "hold_s": hold})

    def _do_corrupt_chunk(self) -> Optional[dict]:
        for root in self._ckpt_dirs:
            for name in sorted(os.listdir(root)) if os.path.isdir(root) else []:
                if not name.startswith("replay_inc"):
                    continue
                path = pick_chunk(os.path.join(root, name), rng=self._rng)
                if path is not None:
                    return self._record(corrupt_chunk(path, rng=self._rng))
        return self._record({"fault": "corrupt_chunk",
                             "skipped": "no committed chunks"})

    def _do_stuck_stager(self) -> dict:
        hold = float(self.cfg.stuck_stager_hold_s)
        self._stager_stall.set()
        self._stop.wait(hold)
        self._stager_stall.clear()
        return self._record({"fault": "stuck_stager", "hold_s": hold})

    def _do_kill_shard(self) -> Optional[dict]:
        """SIGKILL one live replay-service shard (seeded victim) — the
        mid-run shard-death drill the fleet's respawn + checkpoint-chain
        recovery exists for (replay/service.py)."""
        fleet = self._replay_fleet
        if fleet is None:
            return self._record({"fault": "kill_shard",
                                 "skipped": "no replay fleet attached"})
        return self._record(fleet.kill_random(rng=self._rng))

    def _do_shm_fill(self) -> dict:
        rec = self._filler.fill(int(self.cfg.shm_fill_bytes))
        self._stop.wait(float(self.cfg.shm_fill_hold_s))
        self._filler.release()
        return self._record({**rec, "hold_s": float(self.cfg.shm_fill_hold_s)})
