"""Fleet-wide observability: registry, exporter, lineage, flight recorder.

One layer over four runtime tiers (process actors, fused learner, async
checkpoint writer, serving) — see the module docstrings:

  * ``registry``  — typed counters/gauges/histograms + providers + health
  * ``shm_stats`` — per-worker shared-memory stats blocks (SIGKILL-readable)
  * ``exporter``  — /metrics (Prometheus), /varz (JSON), /healthz
  * ``lineage``   — trace-ID'd experience spans + age-of-experience
  * ``recorder``  — flight recorder + post-mortem dumps
  * ``trace``     — /varz?trace=1 on-demand jax.profiler capture

Import-light by contract (stdlib + numpy + utils.metrics): worker
children import ``shm_stats``/``recorder`` before jax exists.
"""

from ape_x_dqn_tpu.obs.exporter import ObsServer
from ape_x_dqn_tpu.obs.lineage import LineageTracker
from ape_x_dqn_tpu.obs.recorder import FlightRecorder, write_postmortem
from ape_x_dqn_tpu.obs.registry import (
    Counter,
    Gauge,
    Health,
    Histogram,
    MetricsRegistry,
)
from ape_x_dqn_tpu.obs.shm_stats import WORKER_SLOTS, WorkerStatsBlock
from ape_x_dqn_tpu.obs.trace import TraceOnDemand

__all__ = [
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Health",
    "Histogram",
    "LineageTracker",
    "MetricsRegistry",
    "ObsServer",
    "TraceOnDemand",
    "WORKER_SLOTS",
    "WorkerStatsBlock",
    "write_postmortem",
]
