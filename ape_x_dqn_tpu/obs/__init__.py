"""Fleet-wide observability: registry, exporter, lineage, flight recorder.

One layer over four runtime tiers (process actors, fused learner, async
checkpoint writer, serving) — see the module docstrings:

  * ``registry``  — typed counters/gauges/histograms + providers + health
  * ``shm_stats`` — per-worker shared-memory stats blocks (SIGKILL-readable)
  * ``exporter``  — /metrics (Prometheus), /varz (JSON), /healthz
  * ``lineage``   — trace-ID'd experience spans + age-of-experience
  * ``recorder``  — flight recorder + post-mortem dumps
  * ``trace``     — /varz?trace=1 on-demand jax.profiler capture

Lazy by contract (PEP 562): worker children import ``shm_stats`` before
jax exists, and ``import ape_x_dqn_tpu.obs.shm_stats`` executes this
file first — so the re-exports below resolve on first attribute access
instead of importing the exporter/lineage/trace stack eagerly (enforced
by the ``import-light`` checker).
"""

from __future__ import annotations

import importlib

_LAZY = {
    "ObsServer": "ape_x_dqn_tpu.obs.exporter",
    "LineageTracker": "ape_x_dqn_tpu.obs.lineage",
    "TraceSpanLog": "ape_x_dqn_tpu.obs.lineage",
    "FleetAggregator": "ape_x_dqn_tpu.obs.fleet",
    "SloEngine": "ape_x_dqn_tpu.obs.fleet",
    "SloRule": "ape_x_dqn_tpu.obs.fleet",
    "FlightRecorder": "ape_x_dqn_tpu.obs.recorder",
    "write_postmortem": "ape_x_dqn_tpu.obs.recorder",
    "Counter": "ape_x_dqn_tpu.obs.registry",
    "Gauge": "ape_x_dqn_tpu.obs.registry",
    "Health": "ape_x_dqn_tpu.obs.registry",
    "Histogram": "ape_x_dqn_tpu.obs.registry",
    "MetricsRegistry": "ape_x_dqn_tpu.obs.registry",
    "WORKER_SLOTS": "ape_x_dqn_tpu.obs.shm_stats",
    "WorkerStatsBlock": "ape_x_dqn_tpu.obs.shm_stats",
    "TraceOnDemand": "ape_x_dqn_tpu.obs.trace",
}

__all__ = sorted(_LAZY)


def __getattr__(name):
    target = _LAZY.get(name)
    if target is not None:
        return getattr(importlib.import_module(target), name)
    try:
        return importlib.import_module(f"{__name__}.{name}")
    except ModuleNotFoundError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
