"""Per-worker fixed-layout shared-memory stats block — metrics that
survive SIGKILL.

The process-actor transport made the EXPERIENCE path kill-safe
(runtime/shm_ring.py); this is the same discipline for the worker's
METRICS.  Children are deliberately import-light (no jax at module scope,
no logger plumbing), so before this block existed they emitted nothing:
the parent saw env-steps only as a derived count from drained chunks, ε
and per-worker health not at all, and a SIGKILLed worker's last known
state was pure guesswork.  Now every worker incarnation gets one small
``/dev/shm`` segment with a parent-defined slot layout:

  * **Slots** — named f64 cells (env_steps, chunks, ε stats, param
    version, ...).  The worker is the single writer; the parent sweeps
    them on its poll cadence.  An 8-byte aligned store is effectively
    atomic on x86; a torn read would corrupt one display sample of one
    gauge, never program state, so slots carry no locks at all.
  * **Event ring** — ``depth`` fixed 256-byte slots of JSON event records
    (the worker-side flight-recorder mirror, obs/recorder.py).  The writer
    overwrites the oldest slot; a SIGKILL mid-write leaves exactly one
    undecodable slot, which the reader counts as torn and skips — same
    detect-don't-deliver contract as the experience ring's CRC framing.
  * **Heartbeat + seq** — writer-stamped CLOCK_MONOTONIC time (comparable
    across processes on one Linux host) and an update counter, so the
    parent distinguishes "alive but idle" from "dead" without signals.

Lifecycle mirrors the experience ring: the PARENT creates (and at
teardown unlinks) one block per worker incarnation; the worker attaches
as writer.  After a SIGKILL the segment persists until the parent's
salvage pass reads the final slot values and the last events — the
post-mortem record `_salvage_incarnation` writes (runtime/process_actors).

Import-light by contract: stdlib only — worker children import this
before jax exists in their process.
"""

from __future__ import annotations

import json
import os
import struct
import time
from multiprocessing import shared_memory
from typing import Dict, List, Optional, Sequence, Tuple

_MAGIC = b"APXO"   # Obs stats block (was b"APXS", which collided with
#                    the replay cold-span record magic — never persisted
#                    across sessions, so the rename is free)
_VERSION = 1

# Header (64 bytes, all fields 8-byte aligned):
#   0: 4s magic | u32 version
#   8: u64 n_slots
#  16: u64 event ring depth (slots)
#  24: u64 events written (monotone; slot = count % depth)   (writer-owned)
#  32: f64 heartbeat (CLOCK_MONOTONIC seconds)               (writer-owned)
#  40: u64 writer pid                                        (writer-owned)
#  48: u64 seq — bumped once per writer update batch         (writer-owned)
#  56: u64 reserved
_HEADER_SIZE = 64
_IDENT = struct.Struct("<4sIQQ")
_U64 = struct.Struct("<Q")
_F64 = struct.Struct("<d")

_OFF_EV_COUNT = 24
_OFF_HEARTBEAT = 32
_OFF_PID = 40
_OFF_SEQ = 48

_NAMES_SIZE = 2048          # JSON slot-name table, creator-written, fixed
_EVENT_SLOT = 256           # u32 len | JSON payload (truncated)

# The slot vocabulary ProcessActorPool provisions for actor workers — one
# place so the worker writer, the parent sweep, and the dashboard agree.
WORKER_SLOTS: Tuple[str, ...] = (
    "env_steps",        # fleet.step_count (this incarnation)
    "chunks",           # chunks committed to the experience ring
    "transitions",      # transitions across those chunks
    "param_version",    # newest adopted param snapshot
    "eps_mean",         # ε-ladder slice stats for this worker's actors
    "eps_min",
    "eps_max",
    "episodes",         # episode stats reported so far
    "collect_s",        # cumulative seconds inside fleet.collect
    "write_s",          # cumulative seconds writing the experience ring
)


class WorkerStatsBlock:
    """One shared-memory stats block (slots + event ring), SPSC like the
    experience ring: the creator (parent) reads, the attacher (worker)
    writes.  All accessors are safe to call after the writer died."""

    def __init__(self, slots: Optional[Sequence[str]] = None,
                 name: Optional[str] = None, create: bool = True,
                 event_depth: int = 64):
        if create:
            if not slots:
                raise ValueError("creator must define the slot layout")
            names = list(slots)
            blob = json.dumps(names).encode()
            if len(blob) > _NAMES_SIZE:
                raise ValueError(
                    f"slot-name table of {len(blob)} bytes exceeds "
                    f"{_NAMES_SIZE}"
                )
            depth = int(event_depth)
            if depth < 1:
                raise ValueError("event_depth must be >= 1")
            size = (_HEADER_SIZE + _NAMES_SIZE + 8 * len(names)
                    + depth * _EVENT_SLOT)
            from ape_x_dqn_tpu.runtime.shm_ring import create_shared_memory

            self._shm = create_shared_memory("stats", size)
            self._shm.buf[:size] = b"\x00" * size
            _IDENT.pack_into(self._shm.buf, 0, _MAGIC, _VERSION,
                             len(names), depth)
            self._shm.buf[_HEADER_SIZE:_HEADER_SIZE + len(blob)] = blob
            self._names = names
            self._depth = depth
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            magic, version, n_slots, depth = _IDENT.unpack_from(
                self._shm.buf, 0
            )
            if magic != _MAGIC or version != _VERSION:
                raise ValueError(f"not an APXS v{_VERSION} block: {name}")
            blob = bytes(
                self._shm.buf[_HEADER_SIZE:_HEADER_SIZE + _NAMES_SIZE]
            ).split(b"\x00", 1)[0]
            self._names = json.loads(blob)
            if len(self._names) != n_slots:
                raise ValueError(f"corrupt slot-name table in {name}")
            self._depth = int(depth)
            # Writer identity lands at attach, so even a worker killed
            # before its first update leaves an identifiable block.
            _U64.pack_into(self._shm.buf, _OFF_PID, os.getpid())
        self._owner = create
        self._index = {n: i for i, n in enumerate(self._names)}
        self._slots_off = _HEADER_SIZE + _NAMES_SIZE
        self._events_off = self._slots_off + 8 * len(self._names)

    # -- identity ---------------------------------------------------------

    @property
    def name(self) -> str:
        return self._shm.name

    @property
    def slot_names(self) -> List[str]:
        return list(self._names)

    @property
    def pid(self) -> int:
        return _U64.unpack_from(self._shm.buf, _OFF_PID)[0]

    @property
    def seq(self) -> int:
        return _U64.unpack_from(self._shm.buf, _OFF_SEQ)[0]

    @property
    def events_written(self) -> int:
        return _U64.unpack_from(self._shm.buf, _OFF_EV_COUNT)[0]

    # -- writer side (the worker) -----------------------------------------

    def set(self, slot: str, value: float) -> None:
        _F64.pack_into(
            self._shm.buf, self._slots_off + 8 * self._index[slot],
            float(value),
        )

    def add(self, slot: str, delta: float) -> None:
        # Single-writer read-modify-write — no lock needed by contract.
        self.set(slot, self.get(slot) + float(delta))

    def get(self, slot: str) -> float:
        return _F64.unpack_from(
            self._shm.buf, self._slots_off + 8 * self._index[slot]
        )[0]

    def update(self, **slots: float) -> None:
        """Batch slot write + heartbeat + seq bump — the once-per-quantum
        call a worker makes."""
        for k, v in slots.items():
            self.set(k, v)
        self.heartbeat()

    def heartbeat(self) -> None:
        _F64.pack_into(self._shm.buf, _OFF_HEARTBEAT, time.monotonic())
        _U64.pack_into(self._shm.buf, _OFF_SEQ, self.seq + 1)

    def record_event(self, record: Dict) -> None:
        """Append one JSON event to the ring (oldest slot overwritten).
        Payload is truncated to the slot size — flight-recorder events are
        small by design; a truncated one decodes as torn, never as lies."""
        payload = json.dumps(record).encode()[:_EVENT_SLOT - 4]
        count = self.events_written
        off = self._events_off + (count % self._depth) * _EVENT_SLOT
        # Payload first, length last, count bump last of all: a SIGKILL
        # between any two stores leaves a slot that fails to decode (stale
        # length over new bytes, or an unbumped count hiding the slot).
        self._shm.buf[off + 4:off + 4 + len(payload)] = payload
        struct.pack_into("<I", self._shm.buf, off, len(payload))
        _U64.pack_into(self._shm.buf, _OFF_EV_COUNT, count + 1)

    # -- reader side (the parent; valid after the writer died) -------------

    def heartbeat_age_s(self) -> float:
        t = _F64.unpack_from(self._shm.buf, _OFF_HEARTBEAT)[0]
        if t <= 0.0:
            return float("inf")  # never beat
        return max(0.0, time.monotonic() - t)

    def snapshot(self) -> Dict:
        """All slots plus writer identity/liveness fields — one sweep."""
        out: Dict = {n: self.get(n) for n in self._names}
        out["pid"] = self.pid
        out["seq"] = self.seq
        out["heartbeat_age_s"] = round(self.heartbeat_age_s(), 3)
        out["events_written"] = self.events_written
        return out

    def recent_events(self, max_events: Optional[int] = None) -> Tuple[List[Dict], int]:
        """(events oldest→newest, torn_count): the last ``max_events``
        decodable records.  A slot that fails to frame or parse — the
        writer was killed mid-write, or the record was truncated — counts
        as torn and is skipped, mirroring the experience ring's
        torn-tail accounting."""
        count = self.events_written
        depth = self._depth
        n = min(count, depth, max_events if max_events else depth)
        events: List[Dict] = []
        torn = 0
        for k in range(count - n, count):
            off = self._events_off + (k % depth) * _EVENT_SLOT
            (length,) = struct.unpack_from("<I", self._shm.buf, off)
            if not 0 < length <= _EVENT_SLOT - 4:
                torn += 1
                continue
            raw = bytes(self._shm.buf[off + 4:off + 4 + length])
            try:
                events.append(json.loads(raw))
            except ValueError:
                torn += 1
        return events, torn

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._shm.close()

    def unlink(self) -> None:
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass
