"""Fleet discovery plane — run-token-scoped membership for every tier.

``fleet/registry.py`` hosts the registry (trainer side) and the
announcer/client (member side); both speak the ``F_FANN``/``F_FREP``
kinds registered in ``runtime/net.py``.  Import-light by contract: the
registry runs inside shard/replica/tool processes that must never pay a
jax import.
"""

from ape_x_dqn_tpu.fleet.registry import (  # noqa: F401
    FleetAnnouncer,
    FleetClient,
    FleetRegistry,
    member_doc,
)
