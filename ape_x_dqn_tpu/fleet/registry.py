"""Fleet discovery/registration plane — the run's membership registry.

Until this module the fleets found each other through side channels that
each assumed something fragile: the replay client re-read an endpoints
FILE (shared storage, mtime races), the aggregator polled the same file,
and autopilot-spawned serving replicas needed the DRIVER to hand their
ports to the aggregator (the PR 15 deferred tail).  This module replaces
all three seams with one wire-discipline channel: every fleet member —
replay shard, serving replica, remote worker host — dials the registry
hosted by the trainer, proves the run token at a reject-by-close hello,
and then announces itself over ``F_FANN`` frames; the registry answers
every announce with an ``F_FREP`` membership snapshot, so announcing and
watching are the same cheap round trip.

Wire contract (the fourth protocol on ``runtime/net.py``'s framing):

  * **Hello** (member → registry, once per connection)::

        FLEET_HELLO: 4s magic "APXF" | u32 version | i64 member_id
                     | i64 incarnation | i64 token

    Wrong magic/version/token is rejected BY CLOSE before any framing
    state exists (``bad_hellos``) — port confusion and cross-run strays
    never reach the membership table.  The registry acks with
    ``FLEET_ACK`` ("APXG" | version | token | registry incarnation).

  * **Announces** (``F_FANN``, member → registry): one JSON doc
    ``{"op": "join"|"heartbeat"|"leave"|"sync", "member": {...}}``.
    ``sync`` carries no member — it is the observer's read path (the
    replay client and the aggregator watch membership without joining
    it).  Every accepted announce is answered with one ``F_FREP``
    snapshot ``{"token", "version", "incarnation", "members"}``.

  * **Adversarial decode**: a torn/bitflipped frame is counted
    (``torn_frames``) and retires the connection; an unknown kind is
    counted (``unexpected_kinds``) and retires the connection; an
    undecodable or ill-shaped announce doc is counted
    (``bad_announces``) and retires the connection; an announce whose
    member incarnation is LOWER than the registered one is counted
    (``stale_rejects``) and never mutates membership — exactly the
    torn-ring/stale-worker contract the other three protocols enforce.

Liveness is lease-based: a member not heard from within ``ttl_s`` is
swept out with a ``member_lost`` event (reason ``ttl``); an explicit
``leave`` is immediate (reason ``leave``).  Membership versions are
monotone, so watchers cheaply detect change.

Deliberately import-light (stdlib only): the registry and announcer run
inside no-jax child processes and the lint gate's import-lightness
contract covers this package.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import zlib
from typing import Callable, Dict, Optional

from ape_x_dqn_tpu.runtime.net import (
    Backoff,
    F_FANN,
    F_FREP,
    FLEET_ACK,
    FLEET_ACK_MAGIC,
    FLEET_HELLO,
    FLEET_HELLO_VERSION,
    FLEET_MAGIC,
    FrameParser,
    frame_bytes,
)

_MAX_ANNOUNCE = 1 << 20      # sanity bound: a membership doc is KBs, not GBs
_OPS = ("join", "heartbeat", "leave", "sync")
_MEMBER_KINDS = ("replay_shard", "serving_replica", "worker_host",
                 "trainer", "observer")


def member_id_for(name: str) -> int:
    """Stable i64 id for a member name (the hello's member_id field)."""
    return zlib.crc32(name.encode("utf-8")) & 0x7FFFFFFF


def member_doc(name: str, kind: str, *, host: str = "", port: int = 0,
               incarnation: int = 1, base: int = 0, capacity: int = 0,
               varz_url: str = "", draining: bool = False) -> dict:
    """One membership row, the shape every tier announces and every
    watcher consumes (docs/METRICS.md "Fleet membership schema")."""
    if kind not in _MEMBER_KINDS:
        raise ValueError(f"unknown member kind: {kind}")
    return {
        "name": str(name),
        "kind": str(kind),
        "id": member_id_for(name),
        "host": str(host),
        "port": int(port),
        "incarnation": int(incarnation),
        "base": int(base),
        "capacity": int(capacity),
        "varz_url": str(varz_url),
        "draining": bool(draining),
    }


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class FleetRegistry:
    """The run's membership table, served over the announce wire.

    Hosted by the trainer (or the driving tool): ``serve()`` binds and
    spawns the accept thread plus the TTL sweeper; members dial
    ``host:port`` with the run token.  All mutation flows through
    ``_apply`` under one lock; ``snapshot()`` is what every ``F_FREP``
    carries and what in-process watchers read directly.
    """

    def __init__(self, *, token: int, host: str = "127.0.0.1",
                 port: int = 0, ttl_s: float = 5.0, incarnation: int = 1,
                 on_event: Optional[Callable[..., None]] = None):
        self.token = int(token)
        self.host = str(host)
        self.port = int(port)
        self.ttl_s = float(ttl_s)
        self.incarnation = int(incarnation)
        self._on_event = on_event
        self._lock = threading.Lock()
        self._members: Dict[str, dict] = {}
        self._last_seen: Dict[str, float] = {}
        self.version = 0
        self._counters = {
            "accepted": 0, "bad_hellos": 0, "torn_frames": 0,
            "unexpected_kinds": 0, "bad_announces": 0, "stale_rejects": 0,
            "announces": 0, "joins": 0, "leaves": 0, "expired": 0,
            "replies": 0,
        }
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: list = []

    # -- events / counters -------------------------------------------------

    def _emit(self, name: str, **fields) -> None:
        if self._on_event is not None:
            try:
                self._on_event(name, **fields)
            except Exception:  # noqa: BLE001 — telemetry must not stall membership
                pass

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + n

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._counters)
            out["members"] = len(self._members)
            out["version"] = self.version
        return out

    # -- membership --------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "token": self.token,
                "version": self.version,
                "incarnation": self.incarnation,
                "members": {k: dict(v) for k, v in self._members.items()},
            }

    def members(self, kind: Optional[str] = None) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._members.items()
                    if kind is None or v.get("kind") == kind}

    def _apply(self, op: str, member: Optional[dict]) -> bool:
        """Apply one validated announce; True when membership changed.
        Stale incarnations are counted and REFUSED here — the one gate
        every mutation passes."""
        if op == "sync":
            return False
        if not isinstance(member, dict) or "name" not in member:
            raise ValueError("announce without a member doc")
        doc = member_doc(
            str(member["name"]), str(member.get("kind", "observer")),
            host=str(member.get("host", "")),
            port=int(member.get("port", 0)),
            incarnation=int(member.get("incarnation", 1)),
            base=int(member.get("base", 0)),
            capacity=int(member.get("capacity", 0)),
            varz_url=str(member.get("varz_url", "")),
            draining=bool(member.get("draining", False)),
        )
        name = doc["name"]
        now = time.monotonic()
        with self._lock:
            cur = self._members.get(name)
            if cur is not None and doc["incarnation"] < cur["incarnation"]:
                self._counters["stale_rejects"] += 1
                return False
            if op == "leave":
                if cur is None:
                    return False
                del self._members[name]
                self._last_seen.pop(name, None)
                self.version += 1
                self._counters["leaves"] += 1
                version = self.version
            else:
                fresh = cur is None or cur["incarnation"] < doc["incarnation"]
                changed = cur != doc
                self._members[name] = doc
                self._last_seen[name] = now
                if changed:
                    self.version += 1
                if fresh:
                    self._counters["joins"] += 1
                version = self.version
                if not fresh and not changed:
                    return False
        if op == "leave":
            self._emit("member_lost", member=name, reason="leave",
                       version=version)
        elif fresh:
            self._emit("member_join", member=name, kind=doc["kind"],
                       incarnation=doc["incarnation"], version=version)
        return True

    def sweep(self, now: Optional[float] = None) -> list:
        """Expire members past their lease; returns the names lost.
        Public so tests drive time explicitly."""
        now = time.monotonic() if now is None else float(now)
        lost = []
        with self._lock:
            for name, seen in list(self._last_seen.items()):
                if now - seen > self.ttl_s:
                    member = self._members.pop(name, None)
                    del self._last_seen[name]
                    if member is not None:
                        self.version += 1
                        self._counters["expired"] += 1
                        lost.append((name, self.version))
        for name, version in lost:
            self._emit("member_lost", member=name, reason="ttl",
                       version=version)
        return [name for name, _v in lost]

    # -- the wire ----------------------------------------------------------

    def serve(self) -> "FleetRegistry":
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((self.host, self.port))
        sock.listen(64)
        sock.settimeout(0.25)
        self.port = sock.getsockname()[1]
        self._sock = sock
        for target, name in ((self._accept_loop, "fleet-accept"),
                             (self._sweep_loop, "fleet-sweep")):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="fleet-conn", daemon=True)
            t.start()
            self._threads.append(t)

    def _sweep_loop(self) -> None:
        cadence = max(0.05, min(1.0, self.ttl_s / 4.0))
        while not self._stop.wait(cadence):
            self.sweep()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(5.0)
            hello = _recv_exact(conn, FLEET_HELLO.size)
            ok = False
            if hello is not None:
                try:
                    magic, version, _mid, _inc, token = \
                        FLEET_HELLO.unpack(hello)
                    ok = (magic == FLEET_MAGIC
                          and version == FLEET_HELLO_VERSION
                          and token == self.token)
                except Exception:  # noqa: BLE001 — a malformed hello is rejected by close, below
                    ok = False
            if not ok:
                # Reject by close: wrong magic/version/token never gets
                # framing state, let alone a membership write.
                self._count("bad_hellos")
                return
            conn.sendall(FLEET_ACK.pack(FLEET_ACK_MAGIC,
                                        FLEET_HELLO_VERSION,
                                        self.token, self.incarnation))
            self._count("accepted")
            self._pump(conn)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _pump(self, conn: socket.socket) -> None:
        parser = FrameParser(max_frame=_MAX_ANNOUNCE)
        reply_seq = 0
        conn.settimeout(max(1.0, self.ttl_s))
        while not self._stop.is_set():
            frame = parser.next()
            if frame is None:
                if parser.error is not None:
                    self._count("torn_frames")
                    return
                try:
                    data = conn.recv(1 << 16)
                except socket.timeout:
                    continue
                except OSError:
                    data = b""
                if not data:
                    if parser.pending():
                        # Truncated mid-frame at disconnect: torn.
                        self._count("torn_frames")
                    return
                parser.feed(data)
                continue
            kind, payload = frame
            if kind != F_FANN:
                # Unknown kind on the announce plane: counted, connection
                # retired — never silently ignored.
                self._count("unexpected_kinds")
                return
            try:
                doc = json.loads(bytes(payload).decode("utf-8"))
                op = doc["op"]
                if op not in _OPS:
                    raise ValueError(f"unknown announce op: {op}")
                self._apply(op, doc.get("member"))
            except Exception:  # noqa: BLE001 — a bad announce is counted and retires the connection
                self._count("bad_announces")
                return
            self._count("announces")
            reply_seq += 1
            body = json.dumps(self.snapshot()).encode("utf-8")
            try:
                conn.sendall(frame_bytes(F_FREP, reply_seq, (body,)))
            except OSError:
                return
            self._count("replies")

    def close(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []


class FleetClient:
    """One member-side connection: hello, announce, read the snapshot.

    Connect-on-demand with ``Backoff`` pacing; every announce is a
    request/reply round trip (``F_FANN`` out, ``F_FREP`` back).  A torn
    or unexpected reply retires the connection and raises — callers
    (the announcer thread, the watcher poll) absorb and retry.
    """

    def __init__(self, host: str, port: int, *, token: int,
                 member_id: int = 0, incarnation: int = 1,
                 timeout_s: float = 2.0, seed: int = 0):
        self.host = str(host)
        self.port = int(port)
        self.token = int(token)
        self.member_id = int(member_id)
        self.incarnation = int(incarnation)
        self.timeout_s = float(timeout_s)
        self._sock: Optional[socket.socket] = None
        self._parser: Optional[FrameParser] = None
        self._seq = 0
        self._backoff = Backoff(base_s=0.05, max_s=1.0, seed=seed)
        self.torn_replies = 0
        self.hello_rejects = 0
        self.reconnects = 0

    def set_endpoint(self, host: str, port: int) -> None:
        if (host, port) != (self.host, self.port):
            self.host, self.port = str(host), int(port)
            self._drop()

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None
        self._parser = None
        self._seq = 0

    def _connect(self) -> None:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout_s)
        sock.settimeout(self.timeout_s)
        sock.sendall(FLEET_HELLO.pack(FLEET_MAGIC, FLEET_HELLO_VERSION,
                                      self.member_id, self.incarnation,
                                      self.token))
        ack = _recv_exact(sock, FLEET_ACK.size)
        if ack is None:
            sock.close()
            self.hello_rejects += 1
            raise ConnectionError("fleet registry rejected the hello")
        magic, _version, token, _reg_inc = FLEET_ACK.unpack(ack)
        if magic != FLEET_ACK_MAGIC or token != self.token:
            sock.close()
            self.hello_rejects += 1
            raise ConnectionError("fleet registry ack mismatch")
        self._sock = sock
        self._parser = FrameParser(max_frame=_MAX_ANNOUNCE)
        self._seq = 0
        self.reconnects += 1
        self._backoff.reset()

    def announce(self, op: str, member: Optional[dict] = None) -> dict:
        """One announce round trip; returns the registry's snapshot."""
        if op not in _OPS:
            raise ValueError(f"unknown announce op: {op}")
        if self._sock is None:
            if not self._backoff.ready():
                raise ConnectionError("fleet registry backoff")
            try:
                self._connect()
            except OSError as e:
                self._backoff.fail()
                raise ConnectionError(f"fleet registry connect: {e}") from e
        try:
            self._seq += 1
            body = json.dumps({"op": op, "member": member}).encode("utf-8")
            self._sock.sendall(frame_bytes(F_FANN, self._seq, (body,)))
            while True:
                frame = self._parser.next()
                if frame is not None:
                    break
                if self._parser.error is not None:
                    self.torn_replies += 1
                    raise ConnectionError(
                        f"torn fleet reply: {self._parser.error}")
                data = self._sock.recv(1 << 16)
                if not data:
                    raise ConnectionError("fleet registry closed")
                self._parser.feed(data)
            kind, payload = frame
            if kind != F_FREP:
                self.torn_replies += 1
                raise ConnectionError(f"unexpected fleet reply kind {kind}")
            return json.loads(bytes(payload).decode("utf-8"))
        except (OSError, ValueError, ConnectionError):
            self._drop()
            self._backoff.fail()
            raise

    def sync(self) -> dict:
        """The observer read path: fetch the snapshot without joining."""
        return self.announce("sync")

    def close(self) -> None:
        self._drop()


class FleetAnnouncer:
    """Member-side lifecycle thread: join, heartbeat, leave.

    One announcer may own SEVERAL member docs (a replay fleet announces
    every shard; a serving fleet every replica) — ``set_member`` adds or
    updates a doc (announced as ``join`` once, ``heartbeat`` after),
    ``remove_member`` announces ``leave``.  With zero members the beat
    degrades to a ``sync`` poll, which is how pure watchers (the replay
    client, the aggregator) ride the same class.  Every successful round
    trip hands the snapshot to ``on_membership`` when its version moved.
    """

    def __init__(self, host: str, port: int, *, token: int,
                 member_id: int = 0, heartbeat_s: float = 1.0,
                 on_membership: Optional[Callable[[dict], None]] = None,
                 on_event: Optional[Callable[..., None]] = None,
                 seed: int = 0):
        self._client = FleetClient(host, port, token=token,
                                   member_id=member_id, seed=seed)
        self.heartbeat_s = float(heartbeat_s)
        self._on_membership = on_membership
        self._on_event = on_event
        self._lock = threading.Lock()
        self._docs: Dict[str, dict] = {}
        self._joined: set = set()
        self._pending_leave: Dict[str, dict] = {}
        self._last_version = -1
        self._membership: dict = {}
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.beats = 0
        self.errors = 0

    # -- member docs -------------------------------------------------------

    def set_member(self, doc: dict) -> None:
        with self._lock:
            name = doc["name"]
            self._docs[name] = dict(doc)
            self._pending_leave.pop(name, None)
        self._wake.set()

    def remove_member(self, name: str) -> None:
        with self._lock:
            doc = self._docs.pop(name, None)
            self._joined.discard(name)
            if doc is not None:
                self._pending_leave[name] = doc
        self._wake.set()

    def membership(self) -> dict:
        with self._lock:
            return dict(self._membership)

    # -- the beat ----------------------------------------------------------

    def poke(self) -> None:
        """Wake the beat thread now (fast propagation after set_member)."""
        self._wake.set()

    def beat_once(self) -> bool:
        """One announce sweep; True when every round trip succeeded.
        Public so tests (and the registry-less unit path) drive it
        synchronously."""
        with self._lock:
            docs = [dict(d) for d in self._docs.values()]
            leaves = dict(self._pending_leave)
            joined = set(self._joined)
        ok = True
        snapshot = None
        for name, doc in leaves.items():
            try:
                snapshot = self._client.announce("leave", doc)
                with self._lock:
                    self._pending_leave.pop(name, None)
            except ConnectionError:
                self.errors += 1
                ok = False
        for doc in docs:
            op = "heartbeat" if doc["name"] in joined else "join"
            try:
                snapshot = self._client.announce(op, doc)
                with self._lock:
                    self._joined.add(doc["name"])
            except ConnectionError:
                self.errors += 1
                ok = False
        if not docs and not leaves:
            try:
                snapshot = self._client.sync()
            except ConnectionError:
                self.errors += 1
                ok = False
        if snapshot is not None:
            self.beats += 1
            self._adopt(snapshot)
        return ok

    def _adopt(self, snapshot: dict) -> None:
        version = int(snapshot.get("version", -1))
        with self._lock:
            moved = version != self._last_version
            if moved:
                self._last_version = version
                self._membership = snapshot
        if moved and self._on_membership is not None:
            try:
                self._on_membership(snapshot)
            except Exception:  # noqa: BLE001 — a sick watcher must not stall heartbeats
                if self._on_event is not None:
                    try:
                        self._on_event("fleet_watch_error", version=version)
                    except Exception:  # noqa: BLE001 — telemetry must not stall heartbeats
                        pass

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.beat_once()
            self._wake.wait(self.heartbeat_s)
            self._wake.clear()

    def start(self) -> "FleetAnnouncer":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop,
                                            name="fleet-announce",
                                            daemon=True)
            self._thread.start()
        return self

    def close(self, leave: bool = True) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=3.0)
            self._thread = None
        if leave:
            with self._lock:
                docs = list(self._docs.values())
                self._docs.clear()
                self._joined.clear()
            for doc in docs:
                try:
                    self._client.announce("leave", doc)
                except ConnectionError:
                    self.errors += 1
        self._client.close()
