"""wire-registry — one registry for frame kinds and protocol magics.

Three protocols (experience, serving, replay RPC) ride one frame
discipline, so ``runtime/net.py`` is the single registry of ``F_*``
frame kinds and wire magics.  Kind values share one namespace (one
parser verifies them all); a duplicated value or a re-declared constant
in ``serving/`` or ``replay/service.py`` is exactly the drift that
turns "torn frame, retired connection" into "silently decoded as the
wrong protocol".

Rules:
  * every ``F_*`` kind is declared exactly once, in net.py, with a
    unique value;
  * no module outside net.py declares an ``F_*`` constant;
  * no comparison tests a kind variable against a raw int literal that
    collides with a registered kind value — always the named constant;
  * a 4-byte ``*MAGIC*`` constant's value is declared by at most one
    module, unless an ``ALLOWED_MAGIC_DUPES`` entry lists the exact
    file set (and then EVERY listed file must declare the identical
    bytes — the allowance is a drift guard, not a hole);
  * wire-plane modules (serving/, replay/service.py) must not declare
    their own magics at all — theirs live in the net.py registry;
  * every registered kind is referenced somewhere (no dead registry
    rows);
  * a function that dispatches on frame kinds must show evidence of an
    explicit rejection path (a torn/reject/bad/close/error identifier)
    — the heuristic teeth behind "handled or explicitly rejected".
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Tuple

from ape_x_dqn_tpu.analysis.core import (
    ALLOWED_MAGIC_DUPES,
    NET_REGISTRY_PATH,
    Finding,
    Repo,
    iter_module_scope,
)

CHECKER = "wire-registry"

_KIND_NAME = re.compile(r"^F_[A-Z0-9_]+$")
_REJECT_VOCAB = re.compile(
    r"torn|reject|unknown|unexpected|bad|err|close|retire|drop|refuse",
    re.IGNORECASE,
)


def _module_scope_assigns(tree: ast.AST):
    for node in iter_module_scope(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            yield node.targets[0].id, node.value, node.lineno


def _kind_decls(repo: Repo, net_path: str) -> Dict[str, Tuple[int, int]]:
    """F_* name -> (value, lineno) in the registry module."""
    tree = repo.tree(net_path)
    out: Dict[str, Tuple[int, int]] = {}
    if tree is None:
        return out
    for name, value, lineno in _module_scope_assigns(tree):
        if _KIND_NAME.match(name) and isinstance(value, ast.Constant) \
                and isinstance(value.value, int):
            out[name] = (value.value, lineno)
    return out


def _magic_decls(repo: Repo):
    """(path, name, bytes value, lineno) for every module-scope 4-byte
    *MAGIC* constant in the scanned tree."""
    for path in repo.files:
        tree = repo.tree(path)
        if tree is None:
            continue
        for name, value, lineno in _module_scope_assigns(tree):
            if "MAGIC" in name and isinstance(value, ast.Constant) \
                    and isinstance(value.value, bytes) \
                    and len(value.value) == 4:
                yield path, name, value.value, lineno


def _is_kindish(node: ast.AST) -> bool:
    if isinstance(node, ast.Name):
        return "kind" in node.id.lower()
    if isinstance(node, ast.Attribute):
        return "kind" in node.attr.lower()
    return False


def check(repo: Repo, net_path: Optional[str] = None,
          allowed_dupes: Optional[dict] = None,
          wire_plane: Optional[Sequence[str]] = None) -> List[Finding]:
    net_path = net_path or NET_REGISTRY_PATH
    allowed = ALLOWED_MAGIC_DUPES if allowed_dupes is None else allowed_dupes
    wire_plane = tuple(wire_plane if wire_plane is not None
                       else ("ape_x_dqn_tpu/serving/",
                             "ape_x_dqn_tpu/replay/service.py"))
    findings: List[Finding] = []

    kinds = _kind_decls(repo, net_path)
    kind_values: Dict[int, List[str]] = {}
    for name, (value, lineno) in kinds.items():
        kind_values.setdefault(value, []).append(name)
    for value, names in sorted(kind_values.items()):
        if len(names) > 1:
            first = sorted(names)[0]
            for name in sorted(names)[1:]:
                findings.append(Finding(
                    checker=CHECKER, path=net_path, line=kinds[name][1],
                    key=f"dup-kind-value:{name}",
                    message=(f"frame kind {name} = {value} collides with "
                             f"{first} — kind values share one namespace "
                             "(one parser verifies all three protocols)"),
                ))
    kind_value_set = {value for value, _lineno in kinds.values()}

    # Magic registry: group declarations by value.
    by_value: Dict[bytes, List[Tuple[str, str, int]]] = {}
    for path, name, value, lineno in _magic_decls(repo):
        by_value.setdefault(value, []).append((path, name, lineno))
        if any(path.startswith(p) if p.endswith("/") else path == p
               for p in wire_plane):
            findings.append(Finding(
                checker=CHECKER, path=path, line=lineno,
                key=f"wire-plane-magic:{path}:{name}",
                message=(f"{name} declares a protocol magic inside the "
                         f"wire plane — magics live once in {net_path} "
                         "(import the name instead)"),
            ))
    for value, decls in sorted(by_value.items()):
        allow = allowed.get(value)
        if len(decls) > 1:
            files = {p for p, _, _ in decls}
            if allow is None or files - set(allow["files"]):
                # The registry module wins the "canonical owner" slot;
                # the finding lands on the other declaration sites.
                decls_sorted = sorted(
                    decls, key=lambda d: (d[0] != net_path, d))
                keep = decls_sorted[0]
                for path, name, lineno in decls_sorted[1:]:
                    findings.append(Finding(
                        checker=CHECKER, path=path, line=lineno,
                        key=f"dup-magic:{path}:{name}",
                        message=(f"magic {value!r} ({name}) is also "
                                 f"declared as {keep[1]} in {keep[0]} — "
                                 "two protocols sharing a magic can be "
                                 "confused at a handshake; register one "
                                 "owner (or an ALLOWED_MAGIC_DUPES entry "
                                 "with a reason)"),
                    ))
    # Verify allowed-dupe entries are intact: every listed file declares
    # exactly that value (drift in any member = finding).
    for value, allow in sorted(allowed.items()):
        declaring = {p for p, _, _ in by_value.get(value, [])}
        for missing in sorted(set(allow["files"]) - declaring):
            if missing in repo.files:
                findings.append(Finding(
                    checker=CHECKER, path=missing, line=0,
                    key=f"dupe-drift:{missing}:{value!r}",
                    message=(f"{missing} is pinned by ALLOWED_MAGIC_DUPES "
                             f"to declare {value!r} but no longer does — "
                             "the blessed duplicate has drifted"),
                ))

    # Package-wide: F_* re-declarations, int-literal kind compares, and
    # decode-dispatch rejection evidence.
    referenced_kinds: set = set()
    for path in repo.files:
        tree = repo.tree(path)
        if tree is None:
            continue
        if path != net_path:
            for name, _value, lineno in _module_scope_assigns(tree):
                if _KIND_NAME.match(name):
                    findings.append(Finding(
                        checker=CHECKER, path=path, line=lineno,
                        key=f"redeclared-kind:{path}:{name}",
                        message=(f"{name} declared outside the registry — "
                                 f"frame kinds live once in {net_path}"),
                    ))
        for node in ast.walk(tree):
            if isinstance(node, ast.Name) and node.id in kinds \
                    and isinstance(node.ctx, ast.Load):
                referenced_kinds.add(node.id)
            if isinstance(node, ast.Compare):
                operands = [node.left] + list(node.comparators)
                if any(_is_kindish(op) for op in operands):
                    for op in operands:
                        if isinstance(op, ast.Constant) \
                                and isinstance(op.value, int) \
                                and not isinstance(op.value, bool) \
                                and op.value in kind_value_set:
                            findings.append(Finding(
                                checker=CHECKER, path=path,
                                line=node.lineno,
                                key=(f"kind-literal:{path}:"
                                     f"{op.value}"),
                                message=(
                                    f"kind compared against raw literal "
                                    f"{op.value} — use the registered "
                                    f"F_* name from {net_path} (a "
                                    "renumbered registry would silently "
                                    "diverge from this site)"),
                            ))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                dispatches = False
                vocab_hit = False
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Compare):
                        ops = [sub.left] + list(sub.comparators)
                        if any(isinstance(o, ast.Name) and o.id in kinds
                               for o in ops):
                            dispatches = True
                    if isinstance(sub, ast.Name) \
                            and _REJECT_VOCAB.search(sub.id):
                        vocab_hit = True
                    elif isinstance(sub, ast.Attribute) \
                            and _REJECT_VOCAB.search(sub.attr):
                        vocab_hit = True
                    elif isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str) \
                            and _REJECT_VOCAB.search(sub.value):
                        vocab_hit = True
                if dispatches and not vocab_hit:
                    findings.append(Finding(
                        checker=CHECKER, path=path, line=node.lineno,
                        key=f"no-reject-path:{path}:{node.name}",
                        message=(
                            f"{node.name}() dispatches on frame kinds but "
                            "shows no explicit rejection path (no torn/"
                            "reject/bad/close/error identifier) — unknown "
                            "kinds must be counted and refused, never "
                            "silently ignored"),
                    ))

    # Dead registry rows: a kind nobody references outside its own
    # declaration line.
    for name, (value, lineno) in sorted(kinds.items()):
        if name not in referenced_kinds:
            findings.append(Finding(
                checker=CHECKER, path=net_path, line=lineno,
                key=f"dead-kind:{name}",
                message=(f"frame kind {name} = {value} is registered but "
                         "never referenced — dead registry rows hide real "
                         "coverage gaps"),
            ))
    return findings
