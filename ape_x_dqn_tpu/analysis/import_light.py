"""import-light — the no-jax-in-children contract, proven statically.

The repo's child processes live or die by spawn latency: replay shards
respawn under RespawnPolicy backoff mid-run, host_join attaches a whole
remote host's workers, and the bench's producer processes fork per
section.  All of them import a contracted set of modules — and none of
those may reach jax/flax/optax through ANY transitive module-scope
import, because one heavy import turns a sub-second respawn into a
multi-second fleet stall (and on a tunneled platform, a device grab).

The proof is a static module-graph walk: module-scope imports only
(function-scope imports are lazy by construction — the repo's blessed
escape hatch), with package ``__init__`` chains included, because
``import a.b.c`` executes ``a/__init__.py`` and ``a/b/__init__.py``
whether the importer wanted them or not.  That __init__ semantics is
exactly how jax used to leak into every "light" module here.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ape_x_dqn_tpu.analysis.core import (
    HEAVY_IMPORTS,
    IMPORT_LIGHT_CONTRACT,
    Finding,
    Repo,
    iter_module_scope,
)

CHECKER = "import-light"


def _module_scope_imports(tree: ast.AST, module: str, is_pkg: bool):
    """Yield (dotted_target, lineno, from_names) for every import that
    executes at module import time.  Relative imports resolve against
    ``module`` (whose package is itself when ``is_pkg``)."""
    pkg_parts = module.split(".") if is_pkg else module.split(".")[:-1]
    for node in iter_module_scope(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                yield alias.name, node.lineno, None
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                anchor = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                base = ".".join(anchor + ([node.module] if node.module
                                          else []))
            if base:
                yield base, node.lineno, [a.name for a in node.names]


def _edges_for(repo: Repo, path: str, modules: Dict[str, str],
               heavy: frozenset):
    """(internal_edges, heavy_edges) of one module: internal edges are
    (target_module, lineno); heavy edges are (heavy_root, lineno)."""
    tree = repo.tree(path)
    if tree is None:
        return [], []
    module = repo.module_name(path)
    is_pkg = path.endswith("__init__.py")
    internal: List[Tuple[str, int]] = []
    heavy_hits: List[Tuple[str, int]] = []
    for target, lineno, from_names in _module_scope_imports(
            tree, module, is_pkg):
        root = target.split(".")[0]
        if root in heavy:
            heavy_hits.append((root, lineno))
            continue
        candidates = []
        if target in modules or root in modules:
            # Importing a.b.c executes every ancestor package __init__.
            parts = target.split(".")
            for i in range(1, len(parts) + 1):
                prefix = ".".join(parts[:i])
                if prefix in modules:
                    candidates.append(prefix)
            if from_names:
                for name in from_names:
                    sub = f"{target}.{name}"
                    if sub in modules:
                        candidates.append(sub)
        for cand in candidates:
            internal.append((cand, lineno))
    return internal, heavy_hits


def check(repo: Repo, roots: Optional[Sequence[str]] = None,
          heavy: Optional[frozenset] = None) -> List[Finding]:
    roots = tuple(roots if roots is not None else IMPORT_LIGHT_CONTRACT)
    heavy = frozenset(heavy if heavy is not None else HEAVY_IMPORTS)
    modules = repo.module_paths()

    # Edge cache: module -> (internal edges, heavy edges).
    cache: Dict[str, Tuple[list, list]] = {}

    def edges(mod: str):
        if mod not in cache:
            cache[mod] = _edges_for(repo, modules[mod], modules, heavy)
        return cache[mod]

    findings: List[Finding] = []
    for root in roots:
        if root not in modules:
            findings.append(Finding(
                checker=CHECKER, path="<contract>", line=0,
                key=f"missing-root:{root}",
                message=(f"import-light contract names {root} but no such "
                         "module exists in the repo — update the contract"),
            ))
            continue
        # BFS with parent pointers for chain reconstruction; ancestor
        # packages of the root itself execute first, so seed them too.
        parent: Dict[str, Optional[str]] = {}
        queue: List[str] = []
        parts = root.split(".")
        for i in range(1, len(parts) + 1):
            prefix = ".".join(parts[:i])
            if prefix in modules and prefix not in parent:
                parent[prefix] = None if prefix == root else root
                queue.append(prefix)
        if root not in parent:
            parent[root] = None
            queue.append(root)
        reported: Set[str] = set()
        while queue:
            mod = queue.pop(0)
            internal, heavy_hits = edges(mod)
            for heavy_root, lineno in heavy_hits:
                if heavy_root in reported:
                    continue
                reported.add(heavy_root)
                chain: List[str] = [mod]
                cur = parent[mod]
                while cur is not None:
                    chain.append(cur)
                    cur = parent[cur]
                chain.reverse()
                findings.append(Finding(
                    checker=CHECKER, path=modules[mod], line=lineno,
                    key=f"{root}->{heavy_root}",
                    message=(
                        f"{root} is contracted jax-free but reaches "
                        f"{heavy_root} at module scope via "
                        f"{' -> '.join(chain)} "
                        f"({modules[mod]}:{lineno}); move the import into "
                        "the function that needs it, or break the chain"
                    ),
                ))
            for target, _lineno in internal:
                if target not in parent:
                    parent[target] = mod
                    queue.append(target)
    return findings
