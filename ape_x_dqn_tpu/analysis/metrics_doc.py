"""metrics-doc — every observable name is documented, statically.

docs/METRICS.md is a contract, not prose: obs tooling (obs_top, the CI
smoke gates, downstream scrapers) parses the JSONL stream and /varz by
the names documented there.  The hand-maintained ``TestMetricsDocSchema``
pins proved section KEY LISTS against live dicts one schema at a time;
this checker generalizes the other half mechanically: every registry
instrument name (``registry.counter/gauge/histogram("...")``), every
``register_provider("...")`` /varz section, and every
``register_jsonl_section("...")`` emit key declared ANYWHERE in the
package must appear (in backticks) in docs/METRICS.md.

Only string-literal names are checkable statically; a dynamically
formatted name (the chaos monkey's per-kind counters) is skipped — the
runtime pins still cover those surfaces.

The module also owns the doc parser the runtime pins share
(:func:`doc_section_keys`), so three copies of ``_doc_keys`` collapse
into one.
"""

from __future__ import annotations

import ast
import os
from typing import List, Optional, Sequence, Tuple

from ape_x_dqn_tpu.analysis.core import METRICS_DOC_PATH, Finding, Repo

CHECKER = "metrics-doc"

_INSTRUMENT_ATTRS = ("counter", "gauge", "histogram")
_REGISTRAR_NAMES = ("register_provider", "register_jsonl_section")

#: Defining modules whose own calls are the primitives, not usages.
_EXCLUDED_PATHS = (
    "ape_x_dqn_tpu/obs/registry.py",
    "ape_x_dqn_tpu/utils/metrics.py",
)


def doc_section_keys(section_header: str,
                     doc_path: Optional[str] = None) -> List[str]:
    """The ``- `key` — …`` names under one ``## …`` header of
    docs/METRICS.md — the parser the runtime schema pins share."""
    if doc_path is None:
        doc_path = os.path.join(
            os.path.dirname(__file__), "..", "..", METRICS_DOC_PATH)
    with open(doc_path, encoding="utf-8") as f:
        text = f.read()
    if section_header not in text:
        return []
    section = text.split(section_header, 1)[1]
    keys: List[str] = []
    for line in section.splitlines():
        line = line.strip()
        if line.startswith("- `"):
            keys.append(line.split("`")[1])
        elif line.startswith("## "):
            break
    return keys


def _declared_names(repo: Repo, excluded: Sequence[str]):
    """(kind, name, path, lineno) for every literal-named instrument or
    section registration in the scanned tree."""
    for path in repo.files:
        if path in excluded:
            continue
        tree = repo.tree(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                name = func.attr
            elif isinstance(func, ast.Name):
                name = func.id
            else:
                continue
            if name in _INSTRUMENT_ATTRS:
                # Guard against stdlib lookalikes: instrument names are
                # slash-or-word metrics paths, never spaces.
                if " " in first.value:
                    continue
                yield "instrument", first.value, path, node.lineno
            elif name in _REGISTRAR_NAMES:
                yield "section", first.value, path, node.lineno


def check(repo: Repo, doc_text: Optional[str] = None,
          doc_path: Optional[str] = None,
          excluded: Optional[Sequence[str]] = None) -> List[Finding]:
    if doc_text is None:
        doc_text = repo.read_doc(doc_path or METRICS_DOC_PATH)
    excluded = tuple(excluded if excluded is not None else _EXCLUDED_PATHS)
    findings: List[Finding] = []
    seen = set()
    for kind, name, path, lineno in _declared_names(repo, excluded):
        key = f"{kind}:{name}"
        if key in seen:
            continue
        seen.add(key)
        if f"`{name}`" not in doc_text:
            what = ("registry instrument" if kind == "instrument"
                    else "JSONL/varz section")
            findings.append(Finding(
                checker=CHECKER, path=path, line=lineno,
                key=key,
                message=(f"{what} `{name}` is registered here but not "
                         f"documented in {METRICS_DOC_PATH} — the schema "
                         "doc is the contract obs tooling parses"),
            ))
    return findings
