"""typed-errors — decode and supervision paths fail typed, never silent.

The repo's whole fault story is typed degradation: torn frames counted
and refused, shard outages surfacing as ``ReplayShardUnavailable``,
restores walking a corrupt chain back LOUDLY.  A bare ``except:`` or a
silent ``except Exception: pass`` in ``runtime/``, ``serving/`` or
``replay/`` is the one construct that can void all of it — a decode
fault swallowed there never becomes a counter, a health transition or a
typed refusal.

Rules:
  * bare ``except:`` — always a finding (it also eats KeyboardInterrupt
    and SystemExit, wedging shutdown);
  * a BROAD handler (``Exception``/``BaseException``) whose body is
    only ``pass``/``continue`` must justify itself IN PLACE with the
    repo's existing convention: a trailing ``# noqa: BLE001 — <reason>``
    comment on the ``except`` line, reason nonempty.  Best-effort
    teardown is legitimate; *unexplained* best-effort is how decode
    bugs hide for six PRs.

Narrow typed handlers (``except OSError: pass``) are exempt: naming the
exception type IS the justification.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Sequence

from ape_x_dqn_tpu.analysis.core import TYPED_ERROR_DIRS, Finding, Repo

CHECKER = "typed-errors"

_BROAD = {"Exception", "BaseException"}
_JUSTIFIED = re.compile(r"#\s*noqa:\s*BLE001\b(?P<reason>.*)$")


def _is_broad(type_node: Optional[ast.AST]) -> bool:
    if type_node is None:
        return True
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Attribute):
        return type_node.attr in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(el) for el in type_node.elts)
    return False


def _is_silent(body: Sequence[ast.stmt]) -> bool:
    return all(isinstance(stmt, (ast.Pass, ast.Continue)) for stmt in body)


def _has_reason(line: str) -> bool:
    m = _JUSTIFIED.search(line)
    if not m:
        return False
    reason = m.group("reason").strip(" -—–:")
    return sum(c.isalpha() for c in reason) >= 3


def check(repo: Repo, dirs: Optional[Sequence[str]] = None) -> List[Finding]:
    dirs = tuple(dirs if dirs is not None else TYPED_ERROR_DIRS)
    findings: List[Finding] = []
    for path in repo.files:
        if not any(path.startswith(d.rstrip("/") + "/") or path == d
                   for d in dirs):
            continue
        tree = repo.tree(path)
        if tree is None:
            continue
        lines = repo.text(path).splitlines()

        def walk(node, func="<module>", ordinals=None, path=path,
                 lines=lines):
            if ordinals is None:
                ordinals = {}
            for child in ast.iter_child_nodes(node):
                child_func = func
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    child_func = child.name
                    walk(child, child_func, {}, path, lines)
                    continue
                if isinstance(child, ast.ExceptHandler):
                    if child.type is None:
                        n = ordinals.setdefault(("bare", func), 0)
                        ordinals[("bare", func)] = n + 1
                        findings.append(Finding(
                            checker=CHECKER, path=path, line=child.lineno,
                            key=f"bare-except:{path}:{func}:{n}",
                            message=(
                                f"bare `except:` in {func}() — it also "
                                "swallows KeyboardInterrupt/SystemExit; "
                                "name the exception type"),
                        ))
                    elif _is_broad(child.type) and _is_silent(child.body):
                        src = lines[child.lineno - 1] \
                            if child.lineno - 1 < len(lines) else ""
                        if not _has_reason(src):
                            n = ordinals.setdefault(("silent", func), 0)
                            ordinals[("silent", func)] = n + 1
                            findings.append(Finding(
                                checker=CHECKER, path=path,
                                line=child.lineno,
                                key=f"silent-swallow:{path}:{func}:{n}",
                                message=(
                                    f"silent broad swallow in {func}() "
                                    "without justification — narrow the "
                                    "type, surface the failure, or "
                                    "annotate `# noqa: BLE001 — <why "
                                    "best-effort is correct here>`"),
                            ))
                walk(child, child_func, ordinals, path, lines)

        walk(tree)
    return findings
