"""shm-discipline — every shm segment carries the session prefix.

PR 7's leak-guard contract: all repo-created ``/dev/shm`` segments are
named ``apx<APEX_SHM_SESSION>_*`` via ``shm_ring.session_shm_name`` /
``create_shared_memory``, so the conftest leak guard can diff exactly
its own session's segments and concurrent runs never false-positive on
each other.  One raw ``SharedMemory(create=True)`` call site outside the
blessed module silently reintroduces anonymous segments that the guard
cannot attribute — this checker bans that statically.

Attaching (``SharedMemory(name=...)`` with no ``create=True``) is fine
anywhere: attach sites don't mint names.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ape_x_dqn_tpu.analysis.core import SHM_BLESSED_PATH, Finding, Repo

CHECKER = "shm-discipline"


def _creates_segment(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "create" and isinstance(kw.value, ast.Constant) \
                and kw.value.value is True:
            return True
    # SharedMemory(name, create, size): positional create.
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant) \
            and node.args[1].value is True:
        return True
    return False


def check(repo: Repo, blessed: Optional[str] = None) -> List[Finding]:
    blessed = blessed or SHM_BLESSED_PATH
    findings: List[Finding] = []
    for path in repo.files:
        if path == blessed:
            continue
        tree = repo.tree(path)
        if tree is None:
            continue
        func_stack: List[str] = []

        def visit(node, func_stack=func_stack, path=path):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func_stack.append(node.name)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                func_stack.pop()
                return
            if isinstance(node, ast.Call):
                func = node.func
                callee = func.attr if isinstance(func, ast.Attribute) \
                    else (func.id if isinstance(func, ast.Name) else "")
                if callee == "SharedMemory" and _creates_segment(node):
                    where = func_stack[-1] if func_stack else "<module>"
                    findings.append(Finding(
                        checker=CHECKER, path=path, line=node.lineno,
                        key=f"raw-create:{path}:{where}",
                        message=(
                            "SharedMemory(create=True) outside "
                            f"{blessed} — segments must be minted via "
                            "session_shm_name/create_shared_memory so "
                            "the session leak guard can attribute them"),
                    ))
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(tree)
    return findings
