"""apexlint — repo-native static analysis enforcing the fleet's invariants.

Six checkers, each derived from a contract the repo already states in
prose (docstrings, docs/METRICS.md, the leak guard, the adversarial-
decode tests) but until now enforced only by convention:

  ==================  =====================================================
  checker id          contract
  ==================  =====================================================
  import-light        contracted child-process modules never reach
                      jax/flax/optax through any transitive module-scope
                      import (static module-graph walk incl. package
                      ``__init__`` chains)
  wire-registry       every ``F_*`` frame kind / protocol magic declared
                      once in runtime/net.py, unique, no duplicated
                      literals at decode sites
  config-coverage     every ``cfg.<section>.<knob>`` read resolves to a
                      declared field; every declared knob is documented
  metrics-doc         every literal registry instrument / provider /
                      JSONL-section name appears in docs/METRICS.md
  shm-discipline      SharedMemory creation flows through the
                      session-prefix helpers (leak-guard attribution)
  typed-errors        no bare ``except:``; silent broad swallows carry an
                      in-place ``# noqa: BLE001 — reason``
  ==================  =====================================================

See docs/INVARIANTS.md for the operator-facing table (what to do when a
checker fires) and ``python -m tools.lint --help`` for the CLI.  The
package is import-light by its own contract: stdlib only.
"""

from __future__ import annotations

from typing import Callable, Dict, List

from ape_x_dqn_tpu.analysis import (
    config_coverage,
    import_light,
    metrics_doc,
    shm_discipline,
    typed_errors,
    wire_registry,
)
from ape_x_dqn_tpu.analysis.core import (
    BASELINE_PATH,
    Finding,
    LintResult,
    Repo,
    apply_baseline,
    load_baseline,
    run_checkers,
    write_baseline,
)

#: checker id -> Repo -> findings (production defaults; tests call the
#: modules' ``check`` directly with fixture options).
CHECKERS: Dict[str, Callable[[Repo], List[Finding]]] = {
    import_light.CHECKER: import_light.check,
    wire_registry.CHECKER: wire_registry.check,
    config_coverage.CHECKER: config_coverage.check,
    metrics_doc.CHECKER: metrics_doc.check,
    shm_discipline.CHECKER: shm_discipline.check,
    typed_errors.CHECKER: typed_errors.check,
}


def run_all(repo: Repo, only=None) -> List[Finding]:
    return run_checkers(repo, CHECKERS, only=only)


__all__ = [
    "BASELINE_PATH",
    "CHECKERS",
    "Finding",
    "LintResult",
    "Repo",
    "apply_baseline",
    "load_baseline",
    "run_all",
    "write_baseline",
]
