"""config-coverage — no ghost knobs, no undocumented knobs.

The reference codebase this repo replaces had exactly one config bug
class: string-keyed JSON fetched with no schema, so a typo'd key read a
default silently (SURVEY §2 component 9).  ``config.py``'s typed
dataclasses killed that at load time — but an ATTRIBUTE READ of a field
that was later renamed/removed still only fails when that code path
runs, which for chaos/fallback paths can be never-in-CI.  This checker
closes the loop statically, both directions:

  * **ghost knobs**: every ``cfg.<section>.<field>`` attribute read (and
    ``getattr(cfg.<section>, "field", ...)``) in the package must name a
    field declared on that section's dataclass;
  * **undocumented knobs**: every declared field must be mentioned in
    dotted ``section.field`` form in README.md or docs/METRICS.md — a
    knob an operator cannot discover is a knob that gets re-invented.

The read-side heuristic keys on the receiver being named like a config
(``cfg``/``config``/``*_cfg`` …, incl. ``self.cfg``); a section object
held in a differently-named local is invisible to it (documented
limitation — the declaration side and the validate() sweep still cover
those fields).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Tuple

from ape_x_dqn_tpu.analysis.core import (
    CONFIG_DOC_PATHS,
    Finding,
    Repo,
)

CHECKER = "config-coverage"

CONFIG_PATH = "ape_x_dqn_tpu/config.py"
ROOT_CLASS = "ApexConfig"

_CFGISH = re.compile(r"(^|_)(cfg|config|conf)$")


def _declared_sections(repo: Repo, config_path: str, root_class: str):
    """({section: {field: lineno}}, {section: class_name}) parsed from the
    config module: the root dataclass's annotated fields whose annotation
    names another class in the same file are sections; that class's
    annotated fields are the knobs."""
    tree = repo.tree(config_path)
    classes: Dict[str, ast.ClassDef] = {}
    if tree is None:
        return {}, {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            classes[node.name] = node
    root = classes.get(root_class)
    sections: Dict[str, Dict[str, int]] = {}
    if root is None:
        return sections, {}
    names: Dict[str, str] = {}
    for stmt in root.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target,
                                                          ast.Name):
            ann = stmt.annotation
            if isinstance(ann, ast.Name) and ann.id in classes:
                names[stmt.target.id] = ann.id
    for section, cls_name in names.items():
        fields: Dict[str, int] = {}
        for stmt in classes[cls_name].body:
            if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name):
                fields[stmt.target.id] = stmt.lineno
        sections[section] = fields
    return sections, names


def _attr_chain(node: ast.Attribute) -> Optional[List[str]]:
    """['root', 'a', 'b'] for root.a.b, None for non-name roots."""
    parts: List[str] = []
    cur: ast.AST = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        parts.reverse()
        return parts
    return None


def _reads(tree: ast.AST, sections: Dict[str, Dict[str, int]]):
    """Yield (section, field, lineno) for cfg-ish section.field reads,
    including getattr(cfg.section, "field"[, default])."""
    handled_attrs = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "getattr" and len(node.args) >= 2 \
                and isinstance(node.args[0], ast.Attribute) \
                and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            chain = _attr_chain(node.args[0])
            if chain and len(chain) >= 2 and chain[-1] in sections \
                    and _CFGISH.search(chain[-2].lower()):
                yield chain[-1], node.args[1].value, node.lineno
                handled_attrs.add(id(node.args[0]))
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and id(node) not in handled_attrs:
            chain = _attr_chain(node)
            if not chain or len(chain) < 3:
                continue
            # Longest chains only: walking yields sub-attributes too, so
            # key on the section appearing right after a cfg-ish name and
            # exactly one field behind it.
            for i in range(1, len(chain) - 1):
                if chain[i] in sections and _CFGISH.search(
                        chain[i - 1].lower()):
                    yield chain[i], chain[i + 1], node.lineno
                    break


def check(repo: Repo, config_path: Optional[str] = None,
          root_class: str = ROOT_CLASS,
          doc_paths: Optional[Sequence[str]] = None,
          doc_text: Optional[str] = None) -> List[Finding]:
    config_path = config_path or CONFIG_PATH
    doc_paths = tuple(doc_paths if doc_paths is not None
                      else CONFIG_DOC_PATHS)
    findings: List[Finding] = []
    sections, _names = _declared_sections(repo, config_path, root_class)
    if not sections:
        return [Finding(
            checker=CHECKER, path=config_path, line=0,
            key="no-config",
            message=(f"could not parse {root_class} sections out of "
                     f"{config_path} — the checker's model of the config "
                     "module is broken"),
        )]

    # Ghost knobs: reads naming undeclared fields.
    seen_ghosts = set()
    for path in repo.files:
        if path == config_path:
            continue            # declaration + validate() self-reads
        tree = repo.tree(path)
        if tree is None:
            continue
        for section, field, lineno in _reads(tree, sections):
            if field.startswith("__"):
                continue
            if field not in sections[section]:
                key = f"ghost:{section}.{field}"
                if (key, path) in seen_ghosts:
                    continue
                seen_ghosts.add((key, path))
                findings.append(Finding(
                    checker=CHECKER, path=path, line=lineno,
                    key=key,
                    message=(f"reads cfg.{section}.{field} but "
                             f"{section} declares no such field in "
                             f"{config_path} — a ghost knob reads as "
                             "AttributeError only on the path that runs "
                             "it"),
                ))

    # Undocumented knobs: declared fields without a dotted doc mention.
    if doc_text is None:
        doc_text = "\n".join(repo.read_doc(p) for p in doc_paths)
    for section in sorted(sections):
        for field, lineno in sorted(sections[section].items()):
            dotted = f"{section}.{field}"
            if dotted not in doc_text:
                findings.append(Finding(
                    checker=CHECKER, path=config_path, line=lineno,
                    key=f"undocumented:{dotted}",
                    message=(f"config knob {dotted} is declared but "
                             f"mentioned in none of {', '.join(doc_paths)}"
                             " — an operator cannot discover it"),
                ))
    return findings
