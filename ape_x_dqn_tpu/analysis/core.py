"""apexlint core — findings, the repo scanner, and the baseline protocol.

Twelve PRs of distributed machinery rest on invariants that were, until
this module, enforced only by convention and scattered per-PR pin tests:
shard servers and tools must stay jax-free for sub-second spawn, every
wire kind/magic must be registered once, every config knob must be
declared+documented, metrics names must match docs/METRICS.md, shm
segments must carry the session prefix, and failures must stay typed.
``ape_x_dqn_tpu/analysis`` turns each of those contracts into a static
AST/import-graph checker; this module is the shared plumbing.

Deliberately import-light (stdlib only): the lint gate budget in
tools/verify_t1.sh is seconds, and the analysis package itself is part
of the import-lightness contract it enforces.

The suppression protocol: findings carry a STABLE key (no line numbers —
lines drift under unrelated edits), and ``baseline.json`` next to this
module may grandfather a (checker, key) pair *with a one-line reason*.
A baseline entry without a reason is itself an error; a finding not in
the baseline is NEW and fails the CLI.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# The repo's contracts, in one place.  Checkers read these as defaults;
# tests point the same checkers at fixture trees with other values.
# ---------------------------------------------------------------------------

#: Importing any of these at module scope makes a process "heavy": multi-
#: second spawn, a device runtime, GiBs of RSS.  The import-light contract
#: is that the modules below never reach one of these transitively.
HEAVY_IMPORTS = frozenset(
    {"jax", "jaxlib", "flax", "optax", "chex", "orbax", "tensorflow",
     "torch"}
)

#: Modules contracted to run in no-jax child processes (sub-second spawn):
#: the replay shard server path, the by-path-loadable transport codecs,
#: the worker-side shm stats block, the remote-host launcher tools — and
#: this analysis package itself (the lint gate's time budget).
IMPORT_LIGHT_CONTRACT: Tuple[str, ...] = (
    "ape_x_dqn_tpu.replay.service",
    "ape_x_dqn_tpu.runtime.net",
    "ape_x_dqn_tpu.runtime.shm_ring",
    "ape_x_dqn_tpu.obs.shm_stats",
    "ape_x_dqn_tpu.obs.fleet",
    "ape_x_dqn_tpu.fleet",
    "ape_x_dqn_tpu.analysis",
    "tools.xp_transport",
    "tools.host_join",
    "tools.lint",
)

#: Magics that MAY be declared in more than one module, each entry the
#: exact file set allowed to declare that value plus the reason.  The
#: checker verifies the duplication is intact (every listed file declares
#: the identical bytes) — the allowance is a drift GUARD, not a hole.
ALLOWED_MAGIC_DUPES: Dict[bytes, Dict[str, object]] = {
    b"APXT": {
        "files": frozenset({
            "ape_x_dqn_tpu/utils/serialization.py",
            "ape_x_dqn_tpu/runtime/net.py",
            "ape_x_dqn_tpu/runtime/shm_ring.py",
        }),
        "reason": (
            "net.py and shm_ring.py must be loadable BY FILE PATH "
            "(tools/xp_transport.py) without the package import, so they "
            "re-declare serialization.py's APXT record magic; this entry "
            "pins all three to the identical value"
        ),
    },
}

#: Where the wire-kind/magic registry lives (checker: wire-registry).
NET_REGISTRY_PATH = "ape_x_dqn_tpu/runtime/net.py"

#: Files whose frame decode/dispatch sites the wire checker audits for
#: duplicated kind literals (the serving plane + the replay RPC plane
#: named by the contract, plus the registry module itself).
WIRE_PLANE_DIRS: Tuple[str, ...] = (
    "ape_x_dqn_tpu/serving",
    "ape_x_dqn_tpu/replay/service.py",
    "ape_x_dqn_tpu/runtime/net.py",
    "ape_x_dqn_tpu/runtime/transport.py",
)

#: Dirs whose decode and supervision paths must fail typed (checker:
#: typed-errors): no bare ``except:``, and a silent broad swallow must
#: carry an in-place ``# noqa: BLE001 — <reason>`` justification.
TYPED_ERROR_DIRS: Tuple[str, ...] = (
    "ape_x_dqn_tpu/runtime",
    "ape_x_dqn_tpu/serving",
    "ape_x_dqn_tpu/replay",
)

#: The one module allowed to call SharedMemory(create=True) directly —
#: everything else must flow through its session-prefixed helpers
#: (checker: shm-discipline).
SHM_BLESSED_PATH = "ape_x_dqn_tpu/runtime/shm_ring.py"

#: Docs a config knob may be documented in (checker: config-coverage).
CONFIG_DOC_PATHS: Tuple[str, ...] = ("README.md", "docs/METRICS.md")

#: The metrics schema contract doc (checker: metrics-doc).
METRICS_DOC_PATH = "docs/METRICS.md"


# ---------------------------------------------------------------------------
# Findings.
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation.

    ``key`` is the suppression identity: stable under unrelated edits
    (never a line number), unique enough to pin one violation.  ``path``
    and ``line`` are for the human reading the report.
    """

    checker: str
    path: str
    line: int
    key: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.checker}] {self.message}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# The repo scanner: one parse per file, shared by every checker.
# ---------------------------------------------------------------------------

class Repo:
    """Lazy-parsing view of the python files under the scanned roots.

    Paths are repo-relative with ``/`` separators; ``tree``/``text`` are
    cached so six checkers cost one parse per file.  A file that fails
    to parse yields a ``parse-error`` finding instead of an exception —
    the linter must report on a broken tree, not crash with it.
    """

    def __init__(self, root: str,
                 rel_dirs: Sequence[str] = ("ape_x_dqn_tpu", "tools")):
        self.root = os.path.abspath(root)
        self.rel_dirs = tuple(rel_dirs)
        self._texts: Dict[str, str] = {}
        self._trees: Dict[str, Optional[ast.AST]] = {}
        self.parse_failures: List[Finding] = []
        self.files: List[str] = []
        for rel in self.rel_dirs:
            base = os.path.join(self.root, rel)
            if os.path.isfile(base) and base.endswith(".py"):
                self.files.append(rel.replace(os.sep, "/"))
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        full = os.path.join(dirpath, fn)
                        self.files.append(
                            os.path.relpath(full, self.root).replace(
                                os.sep, "/")
                        )
        self.files.sort()

    def text(self, path: str) -> str:
        if path not in self._texts:
            with open(os.path.join(self.root, path), encoding="utf-8") as f:
                self._texts[path] = f.read()
        return self._texts[path]

    def tree(self, path: str) -> Optional[ast.AST]:
        if path not in self._trees:
            try:
                self._trees[path] = ast.parse(self.text(path), filename=path)
            except SyntaxError as e:
                self._trees[path] = None
                self.parse_failures.append(Finding(
                    checker="parse-error", path=path,
                    line=int(e.lineno or 0), key=f"parse:{path}",
                    message=f"file does not parse: {e.msg}",
                ))
        return self._trees[path]

    def module_name(self, path: str) -> str:
        """Dotted module name of a repo-relative path (packages by
        directory; ``pkg/__init__.py`` → ``pkg``)."""
        parts = path[:-3].split("/")          # strip .py
        if parts[-1] == "__init__":
            parts = parts[:-1]
        return ".".join(parts)

    def module_paths(self) -> Dict[str, str]:
        return {self.module_name(p): p for p in self.files}

    def read_doc(self, rel: str) -> str:
        """A non-scanned text file (docs), '' when absent."""
        full = os.path.join(self.root, rel)
        if not os.path.exists(full):
            return ""
        with open(full, encoding="utf-8") as f:
            return f.read()


def iter_module_scope(tree: ast.AST) -> Iterable[ast.AST]:
    """Nodes that execute at module import time: everything except the
    bodies of (async) function definitions and lambdas.  Class bodies,
    module-level ``if``/``try``/``with`` blocks all DO run at import."""
    stack = [tree]
    while stack:
        node = stack.pop()
        for child in ast.iter_child_nodes(node):
            yield child
            if not isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                stack.append(child)


# ---------------------------------------------------------------------------
# Baseline (suppression) protocol.
# ---------------------------------------------------------------------------

BASELINE_PATH = os.path.join(os.path.dirname(__file__), "baseline.json")


def load_baseline(path: Optional[str] = None) -> Dict[Tuple[str, str], dict]:
    """(checker, key) → entry.  Raises ValueError on a malformed file or
    an entry without a nonempty reason — an unjustified suppression is
    itself a contract violation."""
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    out: Dict[Tuple[str, str], dict] = {}
    for entry in data.get("entries", []):
        checker = entry.get("checker")
        key = entry.get("key")
        reason = entry.get("reason", "")
        if not checker or not key:
            raise ValueError(f"baseline entry missing checker/key: {entry}")
        if not isinstance(reason, str) or not reason.strip():
            raise ValueError(
                f"baseline entry for {checker}:{key} has no reason — every "
                "suppression must justify itself"
            )
        out[(checker, key)] = entry
    return out


def write_baseline(findings: Sequence[Finding], path: Optional[str] = None,
                   reason: str = "grandfathered by --write-baseline — "
                   "replace with a real justification") -> None:
    path = path or BASELINE_PATH
    entries = [
        {"checker": f.checker, "key": f.key, "path": f.path,
         "reason": reason, "message": f.message}
        for f in sorted(findings, key=lambda f: (f.checker, f.key))
    ]
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"entries": entries}, f, indent=2)
        f.write("\n")
    os.replace(tmp, path)


@dataclasses.dataclass
class LintResult:
    new: List[Finding]
    suppressed: List[Finding]
    stale_baseline: List[dict]          # entries matching no finding

    @property
    def ok(self) -> bool:
        return not self.new


def apply_baseline(findings: Sequence[Finding],
                   baseline: Dict[Tuple[str, str], dict]) -> LintResult:
    new, suppressed = [], []
    seen = set()
    for f in findings:
        ident = (f.checker, f.key)
        if ident in baseline:
            seen.add(ident)
            suppressed.append(f)
        else:
            new.append(f)
    stale = [entry for ident, entry in sorted(baseline.items())
             if ident not in seen]
    return LintResult(new=new, suppressed=suppressed, stale_baseline=stale)


# ---------------------------------------------------------------------------
# Runner.
# ---------------------------------------------------------------------------

def run_checkers(repo: Repo,
                 checkers: Dict[str, Callable[[Repo], List[Finding]]],
                 only: Optional[Sequence[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for name, fn in checkers.items():
        if only and name not in only:
            continue
        findings.extend(fn(repo))
    findings.extend(repo.parse_failures)
    return sorted(findings, key=lambda f: (f.path, f.line, f.checker, f.key))
