"""Actor layer: batched fleets producing prioritized n-step experience."""

from ape_x_dqn_tpu.actors.pool import (
    ActorFleet,
    Chunk,
    EpisodeStat,
    LocalParamSource,
    build_policy_step,
)

__all__ = ["ActorFleet", "Chunk", "EpisodeStat", "LocalParamSource", "build_policy_step"]
