"""The actor fleet: batched rollouts, ε-ladder, n-step emission, priorities.

The reference runs each actor as its own OS process doing batch-1 torch
inference with a per-step ``print`` on the hot path (reference
actor.py:146-191).  That pattern can't feed a TPU learner (SURVEY §7 hard
parts #3).  The TPU-native inversion implemented here:

  * **One fleet, one forward.**  N actor envs step in lockstep
    (``SyncVectorEnv``); action selection for the whole fleet is a single
    jitted ``policy_step`` (forward + vectorized ε-greedy) — batch = N rides
    the MXU, one host↔device round trip per fleet step instead of N.
  * **ε-ladder preserved**: actor i uses ε^(1+α·i/(N−1)) (reference
    actor.py:111-114), materialized once as a device vector.
  * **Sliding-window n-step with zero extra forwards.**  The fleet keeps a
    host-side history ring of the last ``flush_every + n`` steps (obs,
    action, reward, discount, q-values).  Every ``flush_every`` steps it
    emits ``flush_every`` *overlapping* n-step transitions per actor
    (stride 1 — the paper's emission; the reference's non-overlapping
    window is stride=n, SURVEY §2 component 3) and computes initial
    priorities |R + D·max_a Q(S_{t+n}) − Q(S_t)[A_t]| (the reference's
    max-Q actor rule, actor.py:138-142) **from the q-values already computed
    during action selection** — no second forward pass.
  * Episode boundaries: per-step discount γ·(1−done) folds terminal masking
    into the return math (defect fixed vs. reference, SURVEY §2.8).
    Truncation (time limits) keeps its bootstrap, per the env contract
    (envs/core.py:24-28): a window hitting a truncation at offset k is
    emitted with ``next_obs = S_final`` (the episode's final observation,
    which never feeds the policy) and ``discount = γ^(k+1)``, so the
    LEARNER bootstraps through its live target net every time the sample
    is replayed — the return math still stops at the boundary (no window
    ever crosses into the next episode's states), and no stale
    collection-time Q is ever baked into stored rewards.

Parameter sync mirrors reference actor.py:189-191 (poll every
``sync_every`` fleet steps) against a ``ParamSource`` — any object with a
``get(current_version) -> (params, version) | None`` method (the runtime's
versioned param store, or a trivial local stub in tests).
"""

from __future__ import annotations

from typing import Callable, List, NamedTuple, Sequence

import jax
import numpy as np

from ape_x_dqn_tpu.envs.vector import SyncVectorEnv
from ape_x_dqn_tpu.ops.exploration import epsilon_greedy, epsilon_ladder
from ape_x_dqn_tpu.ops.nstep import nstep_returns_np
from ape_x_dqn_tpu.types import DedupChunk, NStepTransition


class Chunk(NamedTuple):
    """One flush: transitions + actor-computed initial priorities.

    ``transitions`` is an ``NStepTransition`` batch (dense wire format) or,
    with the fleet's ``emit_dedup=True``, a ``DedupChunk`` (each frame
    once + refs) — consumers are wired by the same config knob.
    """

    priorities: np.ndarray        # float32 [M]
    transitions: object           # NStepTransition | DedupChunk, batch M
    actor_steps: int              # fleet env steps this chunk covers


class EpisodeStat(NamedTuple):
    actor_id: int
    episode_return: float
    episode_length: int


def build_policy_step(network, seed: int = 0) -> Callable:
    """Jitted fleet policy: forward + ε-greedy in one XLA program.

    Returns ``(params, obs, epsilons, step) -> (actions, q_values)``; the
    PRNG key is derived in-graph by folding the step counter into the
    seed-derived base key, so the host passes only an int — no key
    threading, and distinct seeds give independent exploration streams.
    """

    @jax.jit
    def policy_step(params, obs, epsilons, step):
        q = network.apply(params, obs)[2]
        rng = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        actions = epsilon_greedy(rng, q, epsilons)
        return actions, q

    return policy_step


class ActorFleet:
    """N lockstep actors producing prioritized n-step chunks.

    Args:
      env_fns: one constructor per actor (reference: ``num_actors``,
        parameters.json:9).
      network: the Q-network (flax module) used for action selection.
      n_step: the n-step horizon (reference ``num_steps``=3).
      gamma: discount (reference parameters.json:14).
      epsilon/epsilon_alpha: ε-ladder parameters (reference 0.4 / 7).
      flush_every: fleet steps between chunk emissions — the analogue of the
        reference's ``n_step_transition_batch_size``=5 flush gate
        (actor.py:181-187), but measured in steps, emitting
        ``flush_every × N`` transitions per flush.
      sync_every: fleet steps between parameter-store polls (reference
        ``Q_network_sync_freq``=500, actor.py:189-191).
    """

    def __init__(
        self,
        env_fns: Sequence[Callable],
        network,
        n_step: int = 3,
        gamma: float = 0.99,
        epsilon: float = 0.4,
        epsilon_alpha: float = 7.0,
        flush_every: int = 16,
        sync_every: int = 500,
        seed: int = 0,
        epsilon_index_offset: int = 0,
        epsilon_total: int | None = None,
        emission: str = "overlapping",
        emit_dedup: bool = False,
        emit_dedup_groups: int = 1,
    ):
        self.envs = SyncVectorEnv(env_fns)
        self.network = network
        self.n_step = int(n_step)
        self.gamma = float(gamma)
        self.flush_every = int(flush_every)
        self.sync_every = int(sync_every)
        # Emission cadence: "overlapping" emits every step as a window start
        # (stride 1, the Ape-X paper's sliding window); "strided" emits only
        # n-aligned starts (stride n — the reference's non-overlapping
        # advance-by-n buffer, reference actor.py:44-70).
        if emission not in ("overlapping", "strided"):
            raise ValueError(f"unknown emission mode: {emission}")
        self.stride = self.n_step if emission == "strided" else 1
        if self.flush_every < self.stride:
            raise ValueError(
                "strided emission needs flush_every >= num_steps (a flush "
                "window shorter than the stride can contain no aligned start)"
            )
        if emit_dedup and self.flush_every < self.n_step:
            raise ValueError(
                "dedup emission needs flush_every >= num_steps — carry refs "
                "reach at most one chunk back (types.DedupChunk contract)"
            )
        N = self.envs.num_envs
        # When this fleet is one shard of a larger actor set (process-
        # parallel workers each own a slice), the ε-ladder spans the GLOBAL
        # actor count and this fleet takes rows [offset, offset+N) — actor
        # identity, and hence exploration diversity, is fleet-placement
        # independent (reference actor.py:111-114 indexes global actor ids).
        total = epsilon_total if epsilon_total is not None else N
        off = int(epsilon_index_offset)
        if off < 0 or off + N > total:
            raise ValueError(
                f"epsilon ladder slice [{off}, {off + N}) exceeds total {total}"
            )
        self._epsilons = epsilon_ladder(epsilon, epsilon_alpha, total)[off:off + N]
        self._policy_step = build_policy_step(network, seed=seed)
        self._obs = self.envs.reset(seed=seed)
        # History ring: H = flush_every + n rows; global step s lives at
        # slot s % H (rotating cursor — no per-step memmove of obs history).
        H = self.flush_every + self.n_step
        obs_shape = self.envs.observation_shape
        self._H = H
        self._hist_obs = np.zeros((H, N, *obs_shape), np.uint8)
        self._hist_action = np.zeros((H, N), np.int32)
        self._hist_reward = np.zeros((H, N), np.float32)
        self._hist_discount = np.zeros((H, N), np.float32)
        self._hist_qmax = np.zeros((H, N), np.float32)
        self._hist_qtaken = np.zeros((H, N), np.float32)
        # Truncation bookkeeping: the final observation of a time-limited
        # episode (valid only where _hist_trunc) — flushed windows point
        # their next_obs here so the learner bootstraps at train time.
        self._hist_trunc = np.zeros((H, N), bool)
        self._hist_trunc_obs = np.zeros((H, N, *obs_shape), np.uint8)
        self._rows = 0          # valid rows in history (grows to H, then stays)
        self._step_count = 0    # total fleet steps
        self.params = None
        self.param_version = -1
        # Dedup emission state (types.DedupChunk): fresh random source ids
        # per fleet INSTANCE — a respawned worker's new fleet bootstraps a
        # self-contained first chunk, so consumers never resolve carry refs
        # across an incarnation gap.  ``emit_dedup_groups`` splits the
        # fleet's actors into that many INDEPENDENT dedup streams (one
        # source each): the sharded dedup ring routes whole sources to
        # shards, so a single fleet must present >= n_shards sources or
        # some shards would starve (runtime/fused_dedup.DedupStager).
        self.emit_dedup = bool(emit_dedup)
        g = int(emit_dedup_groups)
        if g < 1:
            raise ValueError("emit_dedup_groups must be >= 1")
        if g > 1 and not emit_dedup:
            raise ValueError("emit_dedup_groups requires emit_dedup=True")
        if g > N:
            raise ValueError(
                f"emit_dedup_groups {g} exceeds the fleet's {N} actors"
            )
        import os as _os

        self._groups = g
        # Group b owns actor columns [bounds[b], bounds[b+1]).
        self._group_bounds = [round(b * N / g) for b in range(g + 1)]
        self._source = [
            int.from_bytes(_os.urandom(8), "little") >> 1 for _ in range(g)
        ]
        self._chunk_seq = [0] * g
        self._last_U = [0] * g   # previous chunk's total frame count
        self._last_bw = [0] * g  # previous chunk's base window row

    @property
    def num_actors(self) -> int:
        return self.envs.num_envs

    @property
    def step_count(self) -> int:
        """Total fleet steps taken (== per-actor env steps, lockstep)."""
        return self._step_count

    def sync_params(self, source) -> bool:
        """Poll the param source; returns True if new params were adopted.

        Snapshots arrive as host (numpy) pytrees — the store's wire format —
        and are uploaded to device once here, so the per-step policy call
        never re-transfers params.
        """
        got = source.get(self.param_version)
        if got is None:
            return False
        params, self.param_version = got
        self.params = jax.device_put(params)
        return True

    def _roll_in(self, obs, action, reward, discount, qmax, qtaken,
                 trunc=None, final_obs=None):
        """Write one fleet step at the rotating cursor slot s % H."""
        slot = self._step_count % self._H
        self._hist_obs[slot] = obs
        self._hist_action[slot] = action
        self._hist_reward[slot] = reward
        self._hist_discount[slot] = discount
        self._hist_qmax[slot] = qmax
        self._hist_qtaken[slot] = qtaken
        if trunc is None:
            self._hist_trunc[slot] = False
        else:
            self._hist_trunc[slot] = trunc
            if trunc.any():
                self._hist_trunc_obs[slot][trunc] = final_obs[trunc]
        self._rows = min(self._rows + 1, self._H)

    def _flush(self) -> List[Chunk]:
        """Emit n-step transitions per actor from the history ring (one
        chunk; ``emit_dedup_groups`` > 1 emits one DedupChunk per actor
        group) —
        window starts 0..F-1 of the flush frame (all of them overlapping
        at stride 1; the GLOBALLY n-aligned subset at stride n, the
        reference's non-overlapping emission).  Requires a full ring
        (_rows == H).

        Called after ``_step_count`` was incremented past the newest row, so
        the oldest row (global step ``_step_count − H``) lives at slot
        ``_step_count % H``; ``order`` gathers rows oldest→newest once per
        flush (amortized ~H/F rows of copy per step, vs. H rows per step for
        a shift-down ring).
        """
        n, F, N = self.n_step, self.flush_every, self.num_actors
        order = (np.arange(self._H) + self._step_count) % self._H
        # Window starts 0..F-1; start+n <= H-1 indexes stay in the ring.
        # Strided emission keeps only starts that are multiples of the
        # stride in GLOBAL step numbering (s0 = the oldest row's global
        # step), so windows stay non-overlapping across flush boundaries
        # exactly like the reference's advance-by-n buffer
        # (reference actor.py:44-70).
        starts = np.arange(F)
        if self.stride > 1:
            s0 = self._step_count - self._H
            starts = starts[(s0 + starts) % self.stride == 0]
        S = len(starts)
        rewards = self._hist_reward[order[: F + n - 1]]
        discounts = self._hist_discount[order[: F + n - 1]]
        returns, boot = nstep_returns_np(rewards, discounts, n)  # [F, N]
        returns, boot = returns[starts], boot[starts]            # [S, N]
        next_idx = order[starts + n]
        qtaken = self._hist_qtaken[order[starts]]
        boot_qmax = self._hist_qmax[next_idx]
        truncs = self._hist_trunc[order[: F + n - 1]]  # [F+n-1, N]
        # trunc_k[j, a] = offset k of the truncation that re-targets window
        # (starts[j], a)'s next_obs (−1: none) — index-level so the dense
        # and dedup materializations below share ONE branch structure.
        trunc_k = np.full((S, N), -1, np.int64)
        if truncs.any():
            # Truncation bootstrap (envs/core.py:24-28): a window whose
            # FIRST done is a truncation at offset k re-targets next_obs to
            # the episode's final observation with discount γ^(k+1); the
            # n-step return is already correct (cumulative discount zeroes
            # contributions past the boundary).  Priorities use Q(S_{t+k})
            # — the last Q computed before the final obs — as the bootstrap
            # proxy (the final obs never went through the policy net); the
            # learner restamps with the exact value on first replay.
            qmax_seq = self._hist_qmax[order[: F + n - 1]]
            alive = np.ones(boot.shape, bool)          # no done before k
            for k in range(n):
                m = alive & truncs[starts + k]
                if m.any():
                    boot[m] = self.gamma ** (k + 1)
                    trunc_k[m] = k
                    boot_qmax[m] = qmax_seq[starts + k][m]
                alive &= discounts[starts + k] != 0.0
        # Actor priority rule: |n-step TD error| with max-Q bootstrap
        # (reference actor.py:138-142), per transition (not collapsed).
        td = returns + boot * boot_qmax - qtaken
        priorities = np.abs(td).astype(np.float32)          # [S, N]
        action = self._hist_action[order[starts]]           # [S, N]
        reward = returns.astype(np.float32)
        discount = boot.astype(np.float32)
        if self.emit_dedup:
            return [
                self._build_dedup(
                    g, order, starts, trunc_k, priorities, action, reward,
                    discount,
                )
                for g in range(self._groups)
            ]
        obs = self._hist_obs[order[starts]]            # [S, N, *obs]
        next_obs = self._hist_obs[next_idx]            # [S, N, *obs]
        for k in range(n):
            m = trunc_k == k
            if m.any():
                next_obs[m] = self._hist_trunc_obs[order[starts + k]][m]
        transitions = NStepTransition(
            obs=obs.reshape(S * N, *obs.shape[2:]),
            action=action.reshape(-1),
            reward=reward.reshape(-1),
            discount=discount.reshape(-1),
            next_obs=next_obs.reshape(S * N, *next_obs.shape[2:]),
        )
        return [Chunk(priorities.reshape(-1), transitions, F * N)]

    def _build_dedup(self, g, order, starts, trunc_k, priorities, action,
                     reward, discount) -> Chunk:
        """Assemble group ``g``'s frame-dedup chunk (types.DedupChunk):
        ship only the F NEW step rows for this group's actor columns (all
        H on the group's bootstrap flush) plus truncation extras; windows
        overlapping the previous flush carry negative refs into its tail."""
        n, F = self.n_step, self.flush_every
        H = self._H
        a0, a1 = self._group_bounds[g], self._group_bounds[g + 1]
        Ng = a1 - a0
        bw = 0 if self._chunk_seq[g] == 0 else n  # first NEW window row
        rows = order[bw:H]                        # new step rows, old→new
        step_frames = self._hist_obs[rows][:, a0:a1]   # [H-bw, Ng, *obs]
        obs_shape = step_frames.shape[2:]
        S = len(starts)
        a_grid = np.broadcast_to(np.arange(Ng), (S, Ng))
        s_grid = np.broadcast_to(starts[:, None], (S, Ng))
        in_chunk = s_grid >= bw
        obs_ref = np.where(
            in_chunk,
            (s_grid - bw) * Ng + a_grid,
            # Carry: window row σ (< bw = n) was the previous chunk's
            # window row σ + F, at its step index (σ + F − prev_bw)·Ng + a;
            # negative refs are relative to the previous chunk's END.
            (s_grid + F - self._last_bw[g]) * Ng + a_grid - self._last_U[g],
        ).astype(np.int64)
        next_ref = ((s_grid + n - bw) * Ng + a_grid).astype(np.int64)
        tk = trunc_k[:, a0:a1]
        extras = []
        extra_index: dict = {}
        if (tk >= 0).any():
            for j, a in zip(*np.nonzero(tk >= 0)):
                k = int(tk[j, a])
                t_row = int(starts[j] + k)        # window row of the trunc
                key = (t_row, int(a))
                if key not in extra_index:
                    extra_index[key] = len(extras)
                    extras.append(
                        self._hist_trunc_obs[order[t_row]][a0 + a]
                    )
                next_ref[j, a] = (H - bw) * Ng + extra_index[key]
        U_step = (H - bw) * Ng
        frames = step_frames.reshape(U_step, *obs_shape)
        if extras:
            frames = np.concatenate([frames, np.stack(extras)], axis=0)
        chunk = DedupChunk(
            frames=frames,
            obs_ref=obs_ref.reshape(-1).astype(np.int32),
            next_ref=next_ref.reshape(-1).astype(np.int32),
            action=action[:, a0:a1].reshape(-1),
            reward=reward[:, a0:a1].reshape(-1),
            discount=discount[:, a0:a1].reshape(-1),
            source=self._source[g],
            chunk_seq=self._chunk_seq[g],
            prev_frames=self._last_U[g],
        )
        self._chunk_seq[g] += 1
        self._last_U[g] = frames.shape[0]
        self._last_bw[g] = bw
        return Chunk(
            priorities[:, a0:a1].reshape(-1), chunk, F * Ng
        )

    def collect(
        self,
        num_steps: int,
        param_source=None,
        selector=None,
    ) -> tuple[List[Chunk], List[EpisodeStat]]:
        """Run ``num_steps`` fleet steps; return emitted chunks + episode
        stats.  The synchronous core — the async runtime wraps this in a
        thread; the deterministic test mode calls it directly.

        ``selector`` is the central-inference seam (actor.inference=
        central; serving/central.CentralSelector): when given, action
        selection is ``selector.select(obs, step) -> (actions, q,
        param_version)`` — the fleet holds NO params, ``param_version``
        tracks the serving tier's replies, and the q rows feed the
        priority math exactly as local q values do.  Everything else
        (history ring, n-step emission, priorities, episode stats) is
        identical in both modes.
        """
        if selector is None and self.params is None:
            if param_source is None or not self.sync_params(param_source):
                raise RuntimeError(
                    "ActorFleet has no params — call sync_params or pass param_source"
                )
        chunks: List[Chunk] = []
        stats: List[EpisodeStat] = []
        for _ in range(num_steps):
            if selector is not None:
                actions, q, version = selector.select(
                    self._obs, self._step_count
                )
                actions = np.asarray(actions)
                q = np.asarray(q)
                self.param_version = int(version)
            else:
                # One transfer for both outputs: each device round trip
                # costs fixed latency (tunneled platforms: ~100-250 ms),
                # so the fleet batch size — not the per-actor work — sets
                # the FPS ceiling.
                actions, q = jax.device_get(self._policy_step(
                    self.params, self._obs, self._epsilons, self._step_count
                ))
            vs = self.envs.step(actions)
            done = vs.terminated | vs.truncated
            discount = (self.gamma * (1.0 - done)).astype(np.float32)
            # Truncation: record the episode's final observation (vs.obs —
            # the next policy input is vs.reset_obs, so this frame is
            # otherwise lost).  _flush points truncated windows' next_obs at
            # it with discount γ^(k+1), so the learner bootstraps with its
            # LIVE target net on every replay — baking a collection-time Q
            # into the reward would freeze a stale estimate in the buffer
            # for the slot's whole lifetime.
            trunc = vs.truncated & ~vs.terminated
            self._roll_in(
                self._obs,
                actions,
                vs.reward,
                discount,
                q.max(axis=-1),
                np.take_along_axis(q, actions[:, None], axis=-1)[:, 0],
                trunc=trunc,
                final_obs=vs.obs,
            )
            self._obs = vs.reset_obs
            self._step_count += 1
            for i in np.nonzero(~np.isnan(vs.episode_return))[0]:
                stats.append(
                    EpisodeStat(int(i), float(vs.episode_return[i]), int(vs.episode_length[i]))
                )
            # Flush on ring-fill, then every flush_every steps after — this
            # phase alignment emits every global step as a window start
            # exactly once (flushing on step % flush_every instead would
            # silently drop the first few steps whenever n % flush_every != 0).
            if (
                self._rows == self._H
                and (self._step_count - self._H) % self.flush_every == 0
            ):
                chunks.extend(self._flush())
            if param_source is not None and self._step_count % self.sync_every == 0:
                self.sync_params(param_source)
        return chunks, stats


class LocalParamSource:
    """Trivial in-process param source for tests and the single-process
    driver — the analogue of the reference's manager dict
    (main.py:38, actor.py:106) without the serialization.

    Snapshots are stored as host numpy pytrees (``jax.device_get`` at
    publish).  This is load-bearing, not just the wire format: the learner's
    train step donates its state buffers, so publishing live device arrays
    would hand actors references that die on the next update.
    """

    def __init__(self, params=None):
        self._params = jax.device_get(params) if params is not None else None
        self._version = 0 if params is not None else -1

    def publish(self, params):
        self._params = jax.device_get(params)
        self._version += 1

    def get(self, current_version: int):
        if self._params is None or self._version <= current_version:
            return None
        return self._params, self._version
