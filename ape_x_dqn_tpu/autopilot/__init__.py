"""Elastic autopilot: the SLO-driven capacity controller (ROADMAP item 3).

PR 14 built the SENSOR half — the fleet rollup (``obs/fleet.py
FleetAggregator``) and the declarative SLO engine whose burn-rate
windows emit typed ``slo_breach``/``slo_clear`` events.  This package is
the ACTUATION half: one :class:`AutopilotController` (own thread,
``autopilot.*`` knobs, default off) consuming that event stream plus the
rollup and driving CAPACITY, not just recovery:

  * **actor fleet** — grow/retire worker processes through the pool's
    elastic primitives (``ProcessActorPool.grow``/``retire``: fresh wids
    on the SAME global ε-ladder partition, scale-down via clean drain,
    never SIGKILL) and tune the drain budget / pipeline depth, to hold
    age-of-experience p95 under its bound and ring occupancy in band;
  * **serving fleet** — grow/retire replicas through
    ``ServingFleet.spawn()`` and the router's proven zero-drop
    drain-from-rotation (``retire``), against the QPS-floor / p99 SLOs;
  * **replay fleet** — grow/retire replay shards through
    ``ReplayServiceFleet.grow()``/``retire()`` (live slot-range
    resharding with a digest-proven handoff), against the per-shard
    add-QPS pressure signal (``obs.fleet_slo_replay_add_qps_high`` up,
    ``autopilot.replay_idle_add_qps_per_shard`` down).

Every decision passes the shared guardrails (min/max bounds,
per-direction cooldowns, a hold window against the opposite direction —
hysteresis ON TOP of the SLO engine's burn windows — and one step at a
time), so a flapping signal can never oscillate capacity.  Every action
emits a typed ``autopilot_action`` event naming its triggering rule;
``autopilot.dry_run`` logs decisions without actuating.

Import-light at module scope (stdlib only): the controller lives in the
trainer process, but tools mount it next to an aggregator on hosts that
never import jax.
"""

from __future__ import annotations

import importlib

_LAZY = {
    "AutopilotController": "ape_x_dqn_tpu.autopilot.controller",
    "Guardrails": "ape_x_dqn_tpu.autopilot.controller",
    "ActorPoolActuator": "ape_x_dqn_tpu.autopilot.actuators",
    "ServingFleetActuator": "ape_x_dqn_tpu.autopilot.actuators",
    "ReplayFleetActuator": "ape_x_dqn_tpu.autopilot.actuators",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    return getattr(importlib.import_module(mod), name)


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
