"""The autopilot's decision core: guardrails + the multi-fleet controller.

Control law, per fleet, per tick:

  * **scale up** when any of the fleet's governing SLO rules is in
    breach (the damped ``slo_breach``/``slo_clear`` stream — the SLO
    engine's burn windows already filtered blips out);
  * **scale down** only while every governing rule is green AND the
    fleet's idle rule — evaluated on the controller's OWN burn-window
    engine, so scale-down inherits the same damping — says the capacity
    is sitting unused (serving: per-replica QPS under
    ``autopilot.serving_idle_qps_per_replica``; replay: per-shard add
    QPS under ``autopilot.replay_idle_add_qps_per_shard`` — add RATE,
    not occupancy, because a full ring stays full after a grow and an
    occupancy pair would oscillate);
  * the actor loop's ring-occupancy-high response is a LADDER: tune the
    pool's drain budget up (×2 per action, bounded by
    ``autopilot.drain_tune_max_factor``) before any worker is retired —
    drain harder first, shrink the fleet last;
  * when scale-up is wanted but the fleet is at its ceiling, the actor
    loop degrades the dispatch pipeline to strict depth 1 instead
    (fresher priority write-backs — the same lever the watchdog pulls).

Every decision passes :class:`Guardrails` — min/max bounds,
per-direction cooldowns, a hold window against the opposite direction,
one step at a time — and emits a typed ``autopilot_action`` event.
``dry_run`` evaluates and emits without actuating (cooldowns still
arm, so a dry run previews the REAL decision cadence).

Deterministic where it matters: every entry point takes an explicit
``now`` so tests drive time, and event ingestion is an explicit queue
drained by ``step`` — no hidden clocks, no hidden threads in tests.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ape_x_dqn_tpu.obs.fleet import SloEngine, SloRule

# Which fleet each SLO rule governs and the direction its breach pushes
# (the rule vocabulary of obs/fleet.rules_from_config).  endpoint
# liveness is deliberately absent: dead processes are the SUPERVISOR's
# domain (respawn/quarantine); the autopilot only moves capacity.
DEFAULT_RULE_FLEETS: Dict[str, tuple] = {
    "age_p95_ms": ("actor", "up"),
    "ring_occupancy_floor": ("actor", "up"),
    "ring_occupancy": ("actor", "down"),
    "serving_p99_ms": ("serving", "up"),
    "serving_qps": ("serving", "up"),
    "inference_rtt_p99_ms": ("serving", "up"),
    "replay_add_qps": ("replay", "up"),
}

# Idle (scale-down) rules the controller's OWN burn-window engine owns,
# mapped to the fleet they shrink.  Kept separate from the breach-driven
# map: an idle rule only ever gates scale-down while everything else on
# its fleet is green.
IDLE_RULE_FLEETS: Dict[str, str] = {
    "serving_idle": "serving",
    "replay_idle": "replay",
}

_RECENT = 8


class Guardrails:
    """Shared decision gate: bounds, per-direction cooldowns, a hold
    window against the opposite direction.  ``check`` returns None when
    the action may proceed, else the suppression reason (a short closed
    vocabulary the state section surfaces)."""

    def __init__(self, *, min_size: int, max_size: int,
                 cooldown_up_s: float, cooldown_down_s: float,
                 hold_opposite_s: float):
        self.min_size = int(min_size)
        self.max_size = int(max_size)
        self.cooldown = {"up": float(cooldown_up_s),
                         "down": float(cooldown_down_s)}
        self.hold_opposite_s = float(hold_opposite_s)
        self._last = {"up": None, "down": None}   # direction -> t

    def check(self, direction: str, size: int, now: float,
              busy: bool = False, bounded: bool = True) -> Optional[str]:
        if direction not in ("up", "down"):
            raise ValueError(f"unknown direction: {direction}")
        if busy:
            return "busy"
        if bounded and direction == "up" and size >= self.max_size:
            return "at_max"
        if bounded and direction == "down" and size <= self.min_size:
            return "at_min"
        last = self._last[direction]
        if last is not None and now - last < self.cooldown[direction]:
            return "cooldown"
        opp = "down" if direction == "up" else "up"
        last_opp = self._last[opp]
        if last_opp is not None and now - last_opp < self.hold_opposite_s:
            return "hold"
        return None

    def record(self, direction: str, now: float) -> None:
        self._last[direction] = now

    def remaining(self, direction: str, now: float) -> float:
        last = self._last[direction]
        if last is None:
            return 0.0
        return max(0.0, self.cooldown[direction] - (now - last))


class _Fleet:
    """Per-fleet decision state: the governing rules currently in
    breach, the guardrails, and the attached actuator."""

    def __init__(self, name: str, guard: Guardrails):
        self.name = name
        self.guard = guard
        self.actuator = None
        self.breaching: Dict[str, dict] = {}   # rule -> last breach fields
        self.last_action: Optional[str] = None
        self.last_rule: Optional[str] = None

    def up_breaches(self, rule_fleets) -> List[str]:
        return sorted(r for r in self.breaching
                      if rule_fleets.get(r, (None, None))
                      == (self.name, "up"))

    def down_breaches(self, rule_fleets) -> List[str]:
        return sorted(r for r in self.breaching
                      if rule_fleets.get(r, (None, None))
                      == (self.name, "down"))


class AutopilotController:
    """One controller, two loops — see the module docstring.

    Construction is passive.  Attach actuators (``attach_actor`` /
    ``attach_serving``), subscribe ``on_slo_event`` to the SLO engine,
    then either ``start()`` the poll thread or drive ``step(now=...)``
    deterministically (tests, and the smoke's phase assertions).
    """

    def __init__(self, cfg, *, rollup_fn: Optional[Callable[[], dict]] = None,
                 emit: Optional[Callable[..., None]] = None,
                 rule_fleets: Optional[Dict[str, tuple]] = None):
        self.cfg = cfg
        self._rollup_fn = rollup_fn
        self._emit = emit
        self._rule_fleets = dict(rule_fleets if rule_fleets is not None
                                 else DEFAULT_RULE_FLEETS)
        self._lock = threading.Lock()
        self._events: deque = deque()
        self._fleets: Dict[str, _Fleet] = {}
        self.decisions = 0      # actions decided (incl. dry-run)
        self.actions = 0        # actions actuated
        self.suppressed: Dict[str, int] = {}
        self.recent: deque = deque(maxlen=_RECENT)
        self._last_rollup: dict = {}
        # Idle (scale-down) rules ride the controller's own burn-window
        # engine — same damping discipline as the breach-driven side.
        idle_rules: List[SloRule] = []
        if cfg.serving_idle_qps_per_replica > 0:
            idle_rules.append(SloRule(
                "serving_idle", "lower",
                cfg.serving_idle_qps_per_replica,
                self._serving_qps_per_replica,
            ))
        if getattr(cfg, "replay_idle_add_qps_per_shard", 0.0) > 0:
            idle_rules.append(SloRule(
                "replay_idle", "lower",
                cfg.replay_idle_add_qps_per_shard,
                self._replay_add_qps_per_shard,
            ))
        self._idle = SloEngine(
            idle_rules, window_s=cfg.idle_window_s,
            burn_threshold=0.6, clear_threshold=0.3, min_samples=3,
            emit=self._idle_event,
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- wiring ------------------------------------------------------------

    def _make_fleet(self, name: str, actuator, min_size: int,
                    max_size: int) -> _Fleet:
        fleet = _Fleet(name, Guardrails(
            min_size=min_size, max_size=max_size,
            cooldown_up_s=self.cfg.cooldown_up_s,
            cooldown_down_s=self.cfg.cooldown_down_s,
            hold_opposite_s=self.cfg.hold_opposite_s,
        ))
        fleet.actuator = actuator
        self._fleets[name] = fleet
        return fleet

    def attach_actor(self, actuator) -> "AutopilotController":
        """Actor-fleet actuator (autopilot/actuators.ActorPoolActuator
        shape: size/capacity/busy/scale_up/scale_down/tune_drain/
        drain_factor/tune_pipeline)."""
        self._make_fleet(
            "actor", actuator,
            min_size=self.cfg.actor_min_workers,
            max_size=actuator.capacity(),
        )
        return self

    def attach_serving(self, actuator) -> "AutopilotController":
        """Serving-fleet actuator (ServingFleetActuator shape:
        size/busy/scale_up/scale_down)."""
        self._make_fleet(
            "serving", actuator,
            min_size=self.cfg.serving_min_replicas,
            max_size=self.cfg.serving_max_replicas,
        )
        return self

    def attach_replay(self, actuator) -> "AutopilotController":
        """Replay-fleet actuator (ReplayFleetActuator shape:
        size/busy/scale_up/scale_down over ReplayServiceFleet's
        grow/retire reshard primitives)."""
        self._make_fleet(
            "replay", actuator,
            min_size=self.cfg.replay_min_shards,
            max_size=self.cfg.replay_max_shards,
        )
        return self

    def on_slo_event(self, name: str, **fields) -> None:
        """SLO-engine subscription hook (``SloEngine.subscribe``):
        breach/clear transitions queue here and apply on the next
        ``step`` — the listener never blocks the scrape thread."""
        if name not in ("slo_breach", "slo_clear"):
            return
        with self._lock:
            self._events.append((name, fields))

    def _idle_event(self, name: str, **fields) -> None:
        # The idle engine's own transitions feed the same queue (rule
        # "serving_idle"), so scale-down decisions read like scale-up
        # ones in the state section and the event stream.
        if self._emit is not None:
            try:
                self._emit(name, **fields)
            except Exception:  # noqa: BLE001 — telemetry must not steer capacity
                pass
        with self._lock:
            self._events.append((name, fields))

    def _serving_qps_per_replica(self, rollup: dict) -> Optional[float]:
        srv = (rollup or {}).get("serving") or {}
        fleet = self._fleets.get("serving")
        if fleet is None or fleet.actuator is None:
            return None
        if not srv.get("replicas"):
            return None
        # Prefer the timeline's trailing-window rate over the
        # instantaneous scrape-to-scrape delta: one quiet sweep must not
        # read as idleness and shrink a loaded fleet.
        qps = (srv.get("window") or {}).get("qps")
        if qps is None:
            qps = srv.get("qps")
        if qps is None:
            return None
        return float(qps) / max(1, fleet.actuator.size())

    def _replay_add_qps_per_shard(self, rollup: dict) -> Optional[float]:
        rep = (rollup or {}).get("replay") or {}
        fleet = self._fleets.get("replay")
        if fleet is None or fleet.actuator is None:
            return None
        if not rep.get("shards_alive"):
            return None
        qps = (rep.get("window") or {}).get("add_qps")
        if qps is None:
            qps = rep.get("add_qps")
        if qps is None:
            return None
        return float(qps) / max(1, fleet.actuator.size())

    # -- the decision sweep ------------------------------------------------

    def _drain_events(self) -> None:
        with self._lock:
            events, self._events = list(self._events), deque()
        for name, fields in events:
            rule = fields.get("rule")
            if rule is None:
                continue
            owner = None
            if rule in IDLE_RULE_FLEETS:
                owner = self._fleets.get(IDLE_RULE_FLEETS[rule])
            else:
                fleet_name, _dir = self._rule_fleets.get(rule, (None, None))
                owner = self._fleets.get(fleet_name)
            if owner is None:
                continue
            if name == "slo_breach":
                owner.breaching[rule] = fields
            else:
                owner.breaching.pop(rule, None)

    def step(self, now: Optional[float] = None) -> List[dict]:
        """One decision sweep: ingest queued SLO transitions, evaluate
        the idle rules on a fresh rollup, then decide AT MOST ONE action
        per fleet through the guardrails.  Returns the actions decided
        this sweep (also emitted as ``autopilot_action`` events)."""
        now = time.monotonic() if now is None else float(now)
        self._drain_events()
        if self._rollup_fn is not None:
            try:
                self._last_rollup = self._rollup_fn() or {}
            except Exception:  # noqa: BLE001 — a sick rollup must not stop decisions on queued events
                pass
        if self._idle.rules:
            self._idle.evaluate(self._last_rollup, now=now)
            self._drain_events()   # idle transitions apply THIS sweep
        acted: List[dict] = []
        for fleet in self._fleets.values():
            rec = self._decide(fleet, now)
            if rec is not None:
                acted.append(rec)
        return acted

    def _decide(self, fleet: _Fleet, now: float) -> Optional[dict]:
        act = fleet.actuator
        if act is None:
            return None
        ups = fleet.up_breaches(self._rule_fleets)
        downs = fleet.down_breaches(self._rule_fleets)
        idle_rule = next(
            (r for r, owner in IDLE_RULE_FLEETS.items()
             if owner == fleet.name and r in fleet.breaching), None)
        if ups:
            rule = ups[0]
            reason = fleet.guard.check("up", act.size(), now,
                                       busy=act.busy())
            if reason == "at_max" and fleet.name == "actor":
                # Ceiling ladder: no more workers to add — degrade the
                # dispatch pipeline to strict depth instead (fresher
                # priorities), once.
                tune = getattr(act, "tune_pipeline", None)
                if tune is not None and fleet.guard.check(
                        "up", act.size(), now, bounded=False) is None:
                    return self._fire(fleet, "up", "tune_pipeline", rule,
                                      tune, now)
            if reason is not None:
                self._suppress(fleet, "up", reason)
                return None
            return self._fire(fleet, "up", "scale_up", rule,
                              act.scale_up, now)
        if downs and fleet.name == "actor":
            rule = downs[0]
            # Drain-harder-first ladder: raise the pool's drain budget
            # up to the configured multiple before retiring anyone.
            tune = getattr(act, "tune_drain", None)
            if tune is not None and act.drain_factor() \
                    < self.cfg.drain_tune_max_factor:
                if fleet.guard.check("down", act.size(), now,
                                     bounded=False) is not None:
                    self._suppress(fleet, "down", "cooldown")
                    return None
                return self._fire(fleet, "down", "tune_drain", rule,
                                  tune, now)
            reason = fleet.guard.check("down", act.size(), now)
            if reason is not None:
                self._suppress(fleet, "down", reason)
                return None
            return self._fire(fleet, "down", "scale_down", rule,
                              act.scale_down, now)
        if idle_rule is not None and not ups:
            reason = fleet.guard.check("down", act.size(), now,
                                       busy=act.busy())
            if reason is not None:
                self._suppress(fleet, "down", reason)
                return None
            return self._fire(fleet, "down", "scale_down", idle_rule,
                              act.scale_down, now)
        return None

    def _suppress(self, fleet: _Fleet, direction: str, reason: str) -> None:
        key = f"{fleet.name}:{direction}:{reason}"
        self.suppressed[key] = self.suppressed.get(key, 0) + 1

    def _fire(self, fleet: _Fleet, direction: str, action: str, rule: str,
              fn: Callable[[], Optional[dict]], now: float
              ) -> Optional[dict]:
        size_from = fleet.actuator.size()
        detail: Optional[dict] = None
        if not self.cfg.dry_run:
            try:
                detail = fn()
            except Exception as e:  # noqa: BLE001 — a failed actuation is a counted decision, never a controller crash
                detail = {"error": f"{type(e).__name__}: {e}"}
            if detail is None:
                # The actuator had nothing to move (no grow candidates,
                # no retirable member): a bound in disguise.
                self._suppress(fleet, direction, "exhausted")
                return None
        fleet.guard.record(direction, now)
        self.decisions += 1
        if not self.cfg.dry_run:
            self.actions += 1
        fleet.last_action = action
        fleet.last_rule = rule
        rec = {
            "fleet": fleet.name,
            "action": action,
            "direction": direction,
            "rule": rule,
            "size_from": size_from,
            "size_to": fleet.actuator.size(),
            "dry_run": bool(self.cfg.dry_run),
            "detail": detail,
        }
        self.recent.append(dict(rec, t=round(now, 3)))
        if self._emit is not None:
            try:
                self._emit("autopilot_action", **rec)
            except Exception:  # noqa: BLE001 — telemetry must not steer capacity
                pass
        return rec

    # -- observability -----------------------------------------------------

    def state(self, now: Optional[float] = None) -> dict:
        """The ``autopilot`` JSONL / /varz section (docs/METRICS.md
        "Autopilot schema", doc-pinned)."""
        now = time.monotonic() if now is None else float(now)
        fleets = {}
        for fleet in self._fleets.values():
            act = fleet.actuator
            fleets[fleet.name] = {
                "size": act.size() if act is not None else None,
                "min": fleet.guard.min_size,
                "max": fleet.guard.max_size,
                "busy": bool(act.busy()) if act is not None else False,
                "breaching": sorted(fleet.breaching),
                "last_action": fleet.last_action,
                "last_rule": fleet.last_rule,
                "cooldown_up_s": round(fleet.guard.remaining("up", now), 2),
                "cooldown_down_s": round(
                    fleet.guard.remaining("down", now), 2),
            }
        return {
            "enabled": True,
            "dry_run": bool(self.cfg.dry_run),
            "decisions": self.decisions,
            "actions": self.actions,
            "suppressed": dict(self.suppressed),
            "fleets": fleets,
            "idle": self._idle.status()["rules"],
            "recent": list(self.recent),
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "AutopilotController":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="autopilot", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(float(self.cfg.poll_s)):
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the controller outlives a bad sweep
                pass

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
