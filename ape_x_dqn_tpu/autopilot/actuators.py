"""Concrete actuators binding the controller to the three fleets.

Thin, state-light adapters: every capacity primitive they call is owned
by the fleet object itself (``ProcessActorPool.grow``/``retire``/
``set_drain_budget``, ``ServingFleet.spawn``/``retire``,
``ReplayServiceFleet.grow``/``retire``, ``DispatchPipeline.degrade``) —
the actuator only names the protocol the controller speaks
(``size``/``busy``/``scale_up``/``scale_down`` + the actor loop's
tuning ladder), so unit tests drive the controller with dict-recording
fakes and never spawn a process.
"""

from __future__ import annotations

from typing import Callable, Optional


class ActorPoolActuator:
    """Actor-fleet actuator over a ``ProcessActorPool``.

    ``pipeline_fn`` (optional) resolves the live DispatchPipeline at
    call time — AsyncPipeline constructs it after the pool, so a
    deferred lookup is the only correct binding.
    """

    def __init__(self, pool, *, pipeline_fn: Optional[Callable] = None):
        self._pool = pool
        self._pipeline_fn = pipeline_fn
        self._drain_base = max(1, int(pool.drain_budget_bytes))
        self._pipeline_tuned = False

    def size(self) -> int:
        return len(self._pool.live_workers())

    def capacity(self) -> int:
        return int(self._pool.local_capacity)

    def busy(self) -> bool:
        # Worker spawns are seconds, not minutes; the up-cooldown is the
        # settling window — the pool itself is never "booting".
        return False

    def scale_up(self) -> Optional[dict]:
        grown = self._pool.grow(1)
        return {"wids": grown} if grown else None

    def scale_down(self) -> Optional[dict]:
        wid = self._pool.retire()
        return {"wid": wid} if wid is not None else None

    def drain_factor(self) -> float:
        return self._pool.drain_budget_bytes / self._drain_base

    def tune_drain(self) -> dict:
        """One rung of the drain ladder: double the pool's per-poll
        drain budget (the controller bounds the factor)."""
        budget = self._pool.set_drain_budget(
            self._pool.drain_budget_bytes * 2
        )
        return {"drain_budget_bytes": budget,
                "factor": round(self.drain_factor(), 2)}

    def tune_pipeline(self) -> Optional[dict]:
        """Ceiling fallback: degrade the overlapped dispatch pipeline to
        strict depth 1 (fresher priority write-backs) — once."""
        if self._pipeline_tuned or self._pipeline_fn is None:
            return None
        pipeline = self._pipeline_fn()
        if pipeline is None or getattr(pipeline, "depth", 1) <= 1:
            return None
        pipeline.degrade()
        self._pipeline_tuned = True
        return {"pipeline_depth": pipeline.depth}


class ServingFleetActuator:
    """Serving-fleet actuator over a ``ServingFleet``.

    ``on_scale`` (optional) is called as ``on_scale(kind, rid)`` after
    every actuation — how a driver keeps its aggregator's endpoint set
    in step with the fleet (register a spawned replica's /varz, forget a
    retired one).
    """

    def __init__(self, fleet, *, drain_grace_s: float = 2.0,
                 on_scale: Optional[Callable] = None):
        self._fleet = fleet
        self._grace = float(drain_grace_s)
        self._on_scale = on_scale

    def size(self) -> int:
        return len(self._fleet.active_replicas())

    def busy(self) -> bool:
        # A spawned replica pays a full jax import before it can serve;
        # holding further scale-ups while one boots is the one-step-at-
        # a-time guardrail made physical.
        return bool(self._fleet.booting())

    def _notify(self, kind: str, rid) -> None:
        if self._on_scale is not None and rid is not None:
            try:
                self._on_scale(kind, rid)
            except Exception:  # noqa: BLE001 — observer must not block actuation
                pass

    def scale_up(self) -> Optional[dict]:
        rid = self._fleet.spawn()
        self._notify("spawn", rid)
        return {"rid": rid}

    def scale_down(self) -> Optional[dict]:
        rid = self._fleet.retire(drain_grace_s=self._grace)
        self._notify("retire", rid)
        return {"rid": rid} if rid is not None else None


class ReplayFleetActuator:
    """Replay-fleet actuator over a ``ReplayServiceFleet`` — the third
    autopilot-governed fleet.

    Scale-up is ``fleet.grow()`` (spawn + announce a fresh highest-sid
    shard); scale-down is ``fleet.retire()`` (drain → stop → restore →
    digest-proven re-ingest into the survivors).  Both return None when
    nothing moved (spawn failed, handoff digest mismatch, nothing
    retirable) — the controller books that as ``exhausted``, never a
    crash.  ``on_scale(kind, sid)`` mirrors the serving actuator's
    observer hook so a driver can keep its aggregator in step when it is
    not membership-driven.
    """

    def __init__(self, fleet, *, drain_grace_s: float = 0.5,
                 on_scale: Optional[Callable] = None):
        self._fleet = fleet
        self._grace = float(drain_grace_s)
        self._on_scale = on_scale

    def size(self) -> int:
        return int(self._fleet.num_shards)

    def busy(self) -> bool:
        # One topology change at a time: a reshard in flight (grow's
        # spawn-and-announce or a retire's handoff chain) holds further
        # actuation until the slot-range math is settled.
        return bool(self._fleet.resharding())

    def _notify(self, kind: str, sid) -> None:
        if self._on_scale is not None and sid is not None:
            try:
                self._on_scale(kind, sid)
            except Exception:  # noqa: BLE001 — observer must not block actuation
                pass

    def scale_up(self) -> Optional[dict]:
        sid = self._fleet.grow()
        self._notify("grow", sid)
        return {"sid": sid} if sid is not None else None

    def scale_down(self) -> Optional[dict]:
        sid = self._fleet.retire(drain_grace_s=self._grace)
        self._notify("retire", sid)
        return {"sid": sid} if sid is not None else None
