"""SIGKILL-safe SPSC shared-memory experience ring — the actor→learner
chunk transport for process actors.

The previous transport was pickle-over-``mp.Queue`` (one bounded queue per
worker incarnation): every chunk paid pickle → pipe write → pipe read →
unpickle, at least three full copies plus syscalls, all deserialization
landing on the learner's one dispatch core — and ``mp.Queue`` is not
SIGKILL-safe (a producer killed mid-``put`` leaves the queue's shared write
lock held forever; round-5 finding).  Purpose-built replay transports
(Reverb) use shared-memory flat buffers for exactly this reason.  This ring
is that transport:

  * **Single producer / single consumer** per ring, one ring per worker
    incarnation.  No locks anywhere: the writer owns the write cursor, the
    reader owns the read cursor, and each record commits via a seqlock-style
    commit word — so a worker killed mid-record leaves a *detectably torn
    tail* instead of a held lock, preserving the per-incarnation salvage
    discipline the mp.Queue layout established.
  * **Records are CRC-framed**: ``u32 len | u32 crc32 | i64 seq | payload``.
    The writer copies the payload, then the len+crc words, and writes the
    monotone ``seq`` LAST — the commit.  The reader accepts a record only if
    ``seq`` equals the next expected index AND the payload's crc matches,
    so stale bytes from a previous ring lap and half-written tails are both
    rejected.  The crc covers the payload's head+tail windows
    (``_CRC_WINDOW`` bytes each; the whole payload when it fits twice the
    window, or always under ``crc_full=True``): a SIGKILL cannot reorder
    program-order stores, so a visible commit word proves every payload
    store *executed* — torn tails are caught by the seq mismatch alone, and
    the crc's remaining jobs (alias rejection, store-VISIBILITY ordering on
    the commit path) are boundary phenomena.  Full-payload crc32 costs
    ~0.9 ms per 900 KB chunk on this host — 2x per chunk, it was the
    transport's whole budget.  On weakly-ordered CPUs (non-x86) payload
    stores may become visible after the commit word with no window
    guarantee; construct both ends with ``crc_full=True`` there (the same
    TSO caveat ``process_actors.SharedParamBuffer`` documents).
  * **Backpressure by construction**: the writer blocks (bounded sleep,
    abortable) when ``capacity`` bytes are in flight, publishing a
    ``full_waits`` counter the learner exports as a metric.
  * **Payloads are written once**: ``pack_array_parts`` emits the existing
    ``utils/serialization`` APXT wire format as a header plus the arrays'
    own buffer views, and ``ShmRing.write`` gathers them straight into
    shared memory — no intermediate ``tobytes()`` / ``b"".join`` staging
    copy, no pickle.  The reader copies each record out of the ring once
    and decodes numpy views over that owned buffer (zero further copies
    before replay ingest).

This file is deliberately dependency-light (stdlib + numpy, no package
imports): ``tools/xp_transport.py`` loads it by file path so benchmark
producer processes never pay the package's jax import.

Cursor-torn-word note: the reader publishes its cursor twice (``ridx_b``
then ``ridx_a``); the writer takes ``min(a, b)``, so an update caught
between the two stores only makes the writer conservative (sees less free
space), never lets it overwrite unread bytes.
"""

from __future__ import annotations

import json
import os
import secrets
import struct
import time
import zlib
from multiprocessing import shared_memory
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


def session_shm_name(kind: str) -> str:
    """A /dev/shm segment name carrying this SESSION's token: ``apx<tok>_
    <kind>_<pid>_<rand>``.  ``APEX_SHM_SESSION`` is set once per test
    session / fleet parent and inherited by every child, so tooling (the
    tests/conftest.py leak guard, obs sweeps) can attribute segments to
    their session by prefix instead of scanning /dev/shm system-wide —
    concurrent sessions and unrelated shm users no longer collide."""
    tok = os.environ.get("APEX_SHM_SESSION", "")
    return f"apx{tok}_{kind}_{os.getpid()}_{secrets.token_hex(4)}"


def create_shared_memory(kind: str, size: int) -> shared_memory.SharedMemory:
    """SharedMemory(create=True) under a session-prefixed name (collision
    retried; the random suffix makes one vanishingly rare)."""
    for _ in range(8):
        try:
            return shared_memory.SharedMemory(
                name=session_shm_name(kind), create=True, size=size
            )
        except FileExistsError:
            continue
    # Pathological collision storm — fall back to the interpreter's own
    # psm_ naming rather than fail the fleet spawn.
    return shared_memory.SharedMemory(create=True, size=size)

_RING_MAGIC = b"APXR"
_RING_VERSION = 1

# Header layout (all fields 8-byte aligned; 64 bytes total):
#   0: 4s magic | u32 version
#   8: u64 data capacity (sanity check on attach)
#  16: u64 ridx_a   — reader cursor, written second   (reader-owned)
#  24: u64 ridx_b   — reader cursor, written first    (reader-owned)
#  32: u64 w_started   — records begun                (writer-owned)
#  40: u64 w_committed — records committed            (writer-owned)
#  48: u64 w_bytes     — committed bytes incl. record headers (writer-owned)
#  56: u64 w_full_waits — ring-full backpressure sleeps (writer-owned)
_HEADER_SIZE = 64
_IDENT = struct.Struct("<4sIQ")
_U64 = struct.Struct("<Q")
_REC = struct.Struct("<IIq")  # len, crc32, seq (seq is the commit word)

_OFF_RIDX_A = 16
_OFF_RIDX_B = 24
_OFF_STARTED = 32
_OFF_COMMITTED = 40
_OFF_BYTES = 48
_OFF_FULL_WAITS = 56

_CRC_WINDOW = 4096  # sampled-crc coverage at each payload boundary


def _as_bytes_view(part) -> memoryview:
    """A flat uint8 view of any C-contiguous buffer (bytes, numpy array)."""
    mv = memoryview(part)
    return mv if mv.format == "B" and mv.ndim == 1 else mv.cast("B")


class ShmRing:
    """One SPSC byte ring in a POSIX shared-memory segment.

    The creator (the learner-side pool) is the owner — it reads and, at
    teardown, unlinks.  The attacher (the worker) is the single writer.
    Records may wrap around the ring end (byte-granular split copies), so
    there are no wasted tail slots and no wrap markers.
    """

    def __init__(self, capacity: int, name: Optional[str] = None,
                 create: bool = True, crc_full: bool = False):
        self.capacity = int(capacity)
        self._crc_full = bool(crc_full)
        if create:
            if self.capacity < _REC.size + 1:
                raise ValueError(f"ring capacity {capacity} too small")
            self._shm = create_shared_memory(
                "ring", _HEADER_SIZE + self.capacity
            )
            self._shm.buf[:_HEADER_SIZE] = b"\x00" * _HEADER_SIZE
            _IDENT.pack_into(self._shm.buf, 0, _RING_MAGIC, _RING_VERSION,
                             self.capacity)
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            magic, version, cap = _IDENT.unpack_from(self._shm.buf, 0)
            if magic != _RING_MAGIC or version != _RING_VERSION:
                raise ValueError(f"not an APXR v{_RING_VERSION} ring: {name}")
            if cap != self.capacity:
                raise ValueError(
                    f"ring {name} capacity {cap} != expected {self.capacity}"
                )
        self._owner = create
        # Writer-local state (resumed from the header so a late attach — or
        # a reader that also writes in tests — starts consistent).
        self._widx = self._get(_OFF_BYTES)
        self._wseq = self._get(_OFF_COMMITTED)
        # Reader-local state.
        self._ridx = self._get(_OFF_RIDX_A)
        self._rseq = self._get(_OFF_COMMITTED) if not create else 0
        self.records_read = 0
        self.bytes_read = 0

    # -- shared-header accessors ------------------------------------------

    def _get(self, off: int) -> int:
        return _U64.unpack_from(self._shm.buf, off)[0]

    def _set(self, off: int, value: int) -> None:
        _U64.pack_into(self._shm.buf, off, value)

    @property
    def name(self) -> str:
        return self._shm.name

    @property
    def started(self) -> int:
        """Records the writer has BEGUN (intent mark, pre-payload)."""
        return self._get(_OFF_STARTED)

    @property
    def committed(self) -> int:
        """Records whose commit word landed (counter may lag the commit
        word itself by one if the writer died between the two stores —
        consumers reconcile via ``records_read``)."""
        return self._get(_OFF_COMMITTED)

    @property
    def committed_bytes(self) -> int:
        return self._get(_OFF_BYTES)

    @property
    def full_waits(self) -> int:
        """Writer-side count of ring-full backpressure sleeps."""
        return self._get(_OFF_FULL_WAITS)

    # -- ring byte copies (wrap-aware) ------------------------------------

    def _copy_in(self, pos: int, src: memoryview) -> None:
        off = pos % self.capacity
        n = len(src)
        head = min(n, self.capacity - off)
        base = _HEADER_SIZE
        self._shm.buf[base + off:base + off + head] = src[:head]
        if n > head:
            self._shm.buf[base:base + (n - head)] = src[head:]

    def _copy_out(self, pos: int, n: int) -> bytes:
        off = pos % self.capacity
        head = min(n, self.capacity - off)
        base = _HEADER_SIZE
        out = bytes(self._shm.buf[base + off:base + off + head])
        if n > head:
            out += bytes(self._shm.buf[base:base + (n - head)])
        return out

    # -- crc framing -------------------------------------------------------

    def _crc_range(self, views: Sequence[memoryview], start: int, end: int,
                   crc: int) -> int:
        """crc32 over payload byte range [start, end) across the parts."""
        off = 0
        for v in views:
            ln = len(v)
            s, e = max(start, off), min(end, off + ln)
            if e > s:
                crc = zlib.crc32(v[s - off:e - off], crc)
            off += ln
            if off >= end:
                break
        return crc

    def _crc_parts(self, views: Sequence[memoryview], n: int) -> int:
        if self._crc_full or n <= 2 * _CRC_WINDOW:
            crc = 0
            for v in views:
                crc = zlib.crc32(v, crc)
            return crc
        crc = self._crc_range(views, 0, _CRC_WINDOW, 0)
        return self._crc_range(views, n - _CRC_WINDOW, n, crc)

    def _crc_payload(self, payload: bytes) -> int:
        n = len(payload)
        if self._crc_full or n <= 2 * _CRC_WINDOW:
            return zlib.crc32(payload)
        mv = memoryview(payload)
        return zlib.crc32(mv[n - _CRC_WINDOW:], zlib.crc32(mv[:_CRC_WINDOW]))

    # -- writer side -------------------------------------------------------

    def _reader_cursor(self) -> int:
        # min() of the duplicated words: a torn-between-stores read is
        # merely conservative (see module docstring).
        return min(self._get(_OFF_RIDX_A), self._get(_OFF_RIDX_B))

    def try_write(self, parts: Sequence) -> bool:
        """Commit one record gathered from ``parts`` (buffer views); False
        if the ring lacks space.  The payload is copied into shared memory
        exactly once — no staging concatenation."""
        views = [_as_bytes_view(p) for p in parts]
        n = sum(len(v) for v in views)
        need = _REC.size + n
        if need > self.capacity:
            raise ValueError(
                f"record of {n} bytes cannot fit ring capacity "
                f"{self.capacity} (raise actor.xp_ring_bytes)"
            )
        if self.capacity - (self._widx - self._reader_cursor()) < need:
            return False
        self._set(_OFF_STARTED, self._wseq + 1)  # intent: tail may be torn
        pos = self._widx + _REC.size
        for v in views:
            self._copy_in(pos, v)
            pos += len(v)
        self._copy_in(self._widx, struct.pack("<II", n, self._crc_parts(views, n)))
        # Commit word stores seq+1: freshly zeroed ring bytes (len=0,
        # crc32(b"")=0, seq=0) must never alias a committed empty record.
        self._copy_in(self._widx + 8, struct.pack("<q", self._wseq + 1))
        self._widx += need
        self._wseq += 1
        self._set(_OFF_COMMITTED, self._wseq)
        self._set(_OFF_BYTES, self._widx)
        return True

    def write(self, parts: Sequence, should_stop: Optional[Callable] = None,
              sleep_s: float = 0.001, timeout: Optional[float] = None) -> bool:
        """Blocking write with backpressure: sleep-poll while the ring is
        full, counting ``full_waits``; abort (False) when ``should_stop``
        fires or ``timeout`` elapses."""
        deadline = time.monotonic() + timeout if timeout else None
        while not self.try_write(parts):
            self._set(_OFF_FULL_WAITS, self._get(_OFF_FULL_WAITS) + 1)
            if should_stop is not None and should_stop():
                return False
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(sleep_s)
        return True

    # -- reader side -------------------------------------------------------

    def read_next(self) -> Optional[bytes]:
        """The next committed record's payload (one copy out of the ring),
        or None.  Advances and publishes the read cursor, freeing the
        record's bytes for the writer."""
        hdr = self._copy_out(self._ridx, _REC.size)
        length, crc, seq = _REC.unpack(hdr)
        if seq != self._rseq + 1 or length > self.capacity - _REC.size:
            return None  # no committed record (or stale lap bytes)
        payload = self._copy_out(self._ridx + _REC.size, length)
        if self._crc_payload(payload) != crc:
            return None  # commit word visible before payload — retry later
        self._ridx += _REC.size + length
        self._rseq += 1
        self.records_read += 1
        self.bytes_read += _REC.size + length
        self._set(_OFF_RIDX_B, self._ridx)
        self._set(_OFF_RIDX_A, self._ridx)
        return payload

    def drain(self, max_records: int = 1 << 30) -> List[bytes]:
        out = []
        while len(out) < max_records:
            rec = self.read_next()
            if rec is None:
                break
            out.append(rec)
        return out

    def torn_tail(self) -> bool:
        """After the writer is dead and the ring drained: True iff the
        writer began a record it never committed (killed mid-write)."""
        return self.started > self.records_read

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        self._shm.close()

    def unlink(self) -> None:
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


# ---------------------------------------------------------------------------
# Flat-dict APXT serialization (jax-free twin of utils/serialization for the
# string-keyed array dicts the experience wire carries).  Byte-identical to
# tree_to_bytes on the same dict — pinned by tests/test_shm_ring.py — so
# either side of the transport may use either implementation.
# ---------------------------------------------------------------------------

_APXT_MAGIC = b"APXT"
_APXT_VERSION = 1
_APXT_PREFIX = struct.Struct("<4sIQ")  # magic, version, header_len


def pack_array_parts(arrays: Dict[str, np.ndarray]) -> List:
    """[prefix+manifest bytes, buf0, buf1, ...] for a flat str-keyed dict of
    arrays — concatenating the parts yields exactly
    ``utils.serialization.tree_to_bytes(arrays)`` (jax flattens dicts in
    sorted-key order; so does this).  The array buffers are VIEWS — no copy
    happens until they are gathered into the ring."""
    manifest: List[dict] = []
    bufs: List[np.ndarray] = []
    for key in sorted(arrays):
        arr = np.asarray(arrays[key])
        if not arr.flags.c_contiguous:
            arr = np.ascontiguousarray(arr)
        dtype = str(arr.dtype)
        if dtype == "bfloat16":  # no numpy wire dtype — raw bits, like
            arr = arr.view(np.uint16)  # serialization.tree_to_bytes
            dtype = "bfloat16"
        manifest.append(
            {"path": [{"k": key}], "dtype": dtype, "shape": list(arr.shape)}
        )
        bufs.append(arr)
    header = json.dumps({"leaves": manifest}).encode()
    return [
        _APXT_PREFIX.pack(_APXT_MAGIC, _APXT_VERSION, len(header)),
        header,
        *bufs,
    ]


def unpack_arrays(data, copy: bool = False) -> Dict[str, np.ndarray]:
    """Decode a flat str-keyed APXT payload back to {name: array}.  With
    ``copy=False`` the arrays are read-only views over ``data`` (zero-copy —
    callers that own ``data`` hand them straight to replay ingest)."""
    view = memoryview(data)
    magic, version, header_len = _APXT_PREFIX.unpack_from(view, 0)
    if magic != _APXT_MAGIC:
        raise ValueError("not an APXT payload (bad magic)")
    if version != _APXT_VERSION:
        raise ValueError(f"unsupported APXT version {version}")
    off = _APXT_PREFIX.size
    header = json.loads(bytes(view[off:off + header_len]))
    off += header_len
    out: Dict[str, np.ndarray] = {}
    for entry in header["leaves"]:
        path = entry["path"]
        if len(path) != 1 or "k" not in path[0]:
            raise ValueError(
                "nested payload — this decoder handles flat dicts only; "
                "use utils.serialization.tree_from_bytes"
            )
        shape = tuple(entry["shape"])
        if entry["dtype"] == "bfloat16":
            raise ValueError("bfloat16 experience payloads are unsupported")
        dt = np.dtype(entry["dtype"])
        count = int(np.prod(shape, dtype=np.int64))
        arr = np.frombuffer(view, dt, count, off).reshape(shape)
        off += count * dt.itemsize
        out[path[0]["k"]] = arr.copy() if copy else arr
    return out


# ---------------------------------------------------------------------------
# Experience-record envelope: a fixed metadata prefix + the APXT array dict.
# The prefix carries everything that is NOT an array (message kind, param
# version, send timestamp for latency metrics, per-chunk accounting ints).
# ---------------------------------------------------------------------------

XP = 1    # dense NStepTransition chunk
DXP = 2   # frame-dedup DedupChunk

# kind u8 | pad | version i64 | sent_t f64 (CLOCK_MONOTONIC, comparable
# across processes on one Linux host) | actor_steps i64 | source i64 |
# chunk_seq i64 | prev_frames i64 | trace_id i64 (0 = unsampled; a nonzero
# id marks this chunk for experience-lineage tracing — obs/lineage.py
# follows it actor → ring → ingest → sample → train)
_MSG = struct.Struct("<B7xqdqqqqq")


def encode_chunk_parts(kind: int, version: int, actor_steps: int,
                       arrays: Dict[str, np.ndarray], source: int = 0,
                       chunk_seq: int = 0, prev_frames: int = 0,
                       sent_t: Optional[float] = None,
                       trace_id: int = 0) -> List:
    """Ring-ready parts for one experience chunk (prefix + APXT parts)."""
    prefix = _MSG.pack(
        kind, int(version), sent_t if sent_t is not None else time.monotonic(),
        int(actor_steps), int(source), int(chunk_seq), int(prev_frames),
        int(trace_id),
    )
    return [prefix, *pack_array_parts(arrays)]


def decode_chunk(payload: bytes, copy: bool = False):
    """(kind, version, sent_t, actor_steps, source, chunk_seq, prev_frames,
    trace_id, arrays) from one ring record."""
    (kind, version, sent_t, actor_steps, source, chunk_seq, prev_frames,
     trace_id) = _MSG.unpack_from(payload, 0)
    arrays = unpack_arrays(memoryview(payload)[_MSG.size:], copy=copy)
    return (kind, version, sent_t, actor_steps, source, chunk_seq,
            prev_frames, trace_id, arrays)
